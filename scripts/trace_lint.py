#!/usr/bin/env python
"""Metric-name lint: registry names must be well-formed AND documented.

Walks every registry().counter/gauge/histogram registration in
`celestia_app_tpu/` (AST, no imports — runs in any image) and checks:

  1. the name matches `celestia_[a-z0-9_]+` (static names exactly;
     f-string names on their static prefix), so the exposition namespace
     stays uniform; and
  2. the name appears in the README "Metrics" table (dynamic families may
     be documented with a `<placeholder>` segment, e.g.
     `celestia_block_<stage>_seconds`, matched by prefix), so docs and
     exposition goldens cannot drift apart; and
  3. every explicit LABEL keyword on a metric write (`.inc(...)` /
     `.set(...)` / `.observe(...)`) matches `[a-z][a-z0-9_]*`; and
  4. labels fed from an unbounded-cardinality source (today: `namespace`,
     one value per tenant) only appear in modules that route the value
     through the top-N cap helper
     (trace/square_journal.capped_namespace_label) — a module that slaps
     `namespace=` on a metric without referencing the helper fails,
     which is what keeps the exposition's label cardinality provably
     bounded as tenants multiply; and
  5. in the HOT-PATH modules (parallel/, da/, kernels/, consensus/),
     every `except Exception:` / bare `except:` handler carries a
     `# chaos-ok: <why>` rationale on its line (or the line above).  A
     broad catch on the block path is where a fault gets SWALLOWED
     instead of retried/degraded/propagated (the chaos layer exists
     because of exactly such sites) — the tag forces each one to say why
     swallowing is right.  Existing sites were grandfathered by tagging
     them with their (pre-existing) rationales; and
  6. every path ROUTED in trace/exposition.handle_observability_get —
     an `p == "/x"` equality or a `p.startswith("/x/")` prefix — appears
     in the README endpoint table as a `GET /x` (prefix routes match any
     documented `GET /x/<placeholder>` row).  The shared handler is what
     makes the three planes' observability surface one surface; this
     rule closes the doc-drift loophole where a new endpoint ships on
     every plane but no operator can discover it; and
  7. the fleet surface stays discoverable and the wire trace stays ONE
     trace: (a) every route in trace/fleet.FLEET_ROUTES appears in the
     README endpoint table (the aggregator scrapes peers by these paths,
     so an undocumented fleet route is invisible to the operator wiring
     the fleet up), and (b) any rpc/ module that calls
     `new_context(...)` or `use_context(...)` must also reference
     `adopt_context` or `adopt_or_new` — a serving plane that mints a
     fresh root context on an inbound hop instead of adopting the
     x-celestia-trace header splits the cross-node trace, which is
     exactly the regression the propagation layer exists to prevent.
  8. every module under da/, kernels/, serve/, parallel/ that builds a
     jit program (`jax.jit(...)` call or `@jax.jit` decorator) must
     reference `celestia_app_tpu.trace.device_ledger` — a jit-cache
     family that never registers with the device-attribution ledger is
     invisible on GET /device: its compiles, dispatches, and residency
     vanish from the exact surface built to account for them.
  9. every trace-row write (`.write("table", ...)` with a resolvable
     table name — a string literal or a module-level string constant)
     must stamp `height=` or `trace_id=` (a `**splat` keyword counts:
     the spread row carries the stamps), unless the table is in the
     height-free allowlist (HEIGHT_FREE_TABLES — process-scoped events
     like pages and WAL salvage that genuinely belong to no height).  An
     unstamped row is invisible to the height-anatomy timeline
     (trace/timeline.py): it can never be stitched into a per-height
     critical path, which is exactly the observability gap this plane
     exists to close.  Unresolvable first args (self.TABLE, a local) are
     skipped — the literal-name sites are the enforcement surface.

Run standalone (exit 1 on problems) or via tests/test_trace_lint.py,
which puts the check in tier-1.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "celestia_app_tpu")
README = os.path.join(REPO_ROOT, "README.md")

METRIC_NAME_RE = re.compile(r"^celestia_[a-z0-9_]+$")
METRIC_PREFIX_RE = re.compile(r"^celestia_[a-z0-9_]*$")
README_TOKEN_RE = re.compile(r"celestia_[a-z0-9_<>]+")
REGISTRY_METHODS = {"counter", "gauge", "histogram"}

LABEL_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
METRIC_WRITE_METHODS = {"inc", "set", "observe"}
# Labels whose value space grows with usage (one value per tenant): a
# metric may only carry them when the module routes the value through the
# cardinality cap helper.
UNBOUNDED_LABELS = {"namespace"}
CAP_HELPER = "capped_namespace_label"

# Hot-path module prefixes (package-relative) where a broad exception
# handler must carry a `# chaos-ok:` rationale tag.
HOT_PATH_PREFIXES = ("parallel/", "da/", "kernels/", "consensus/")
CHAOS_OK_TAG = "chaos-ok:"

# Rule 6: the shared observability router + the README table its routes
# must be documented in.
EXPOSITION_REL = os.path.join("celestia_app_tpu", "trace", "exposition.py")
ROUTER_FUNC = "handle_observability_get"
README_ENDPOINT_RE = re.compile(r"GET\s+(/[A-Za-z0-9_/<>-]*)")

# Rule 7: the fleet scrape surface + the adopt-don't-mint discipline on
# the serving planes.
FLEET_REL = os.path.join("celestia_app_tpu", "trace", "fleet.py")
FLEET_ROUTES_NAME = "FLEET_ROUTES"
RPC_PREFIX = "celestia_app_tpu/rpc/"
MINT_FUNCS = {"new_context", "use_context"}
ADOPT_FUNCS = {"adopt_context", "adopt_or_new"}

# Rule 9: trace tables whose rows genuinely belong to no height — page
# events, bundle dumps, WAL salvage, chaos injections are process-scoped.
# Everything else written through the tracer must stamp height= or
# trace_id= so the height-anatomy timeline can stitch it.
TABLE_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
STITCH_KEYS = {"height", "trace_id"}
HEIGHT_FREE_TABLES = {
    "slo_page",
    "flight_dump",
    "wal_salvage",
    "chaos_injection",
    "profiler",        # one capture window per process, not per height
    "hbm_high_water",  # lifetime allocator/RSS peaks, not per height
}


def _parse_package(package_dir: str = PACKAGE_DIR):
    """[(repo-relative path, parsed AST, source lines)] for every .py
    under the package — the single walk+parse all collectors share."""
    out = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
            out.append((
                os.path.relpath(path, REPO_ROOT), tree, source.splitlines()
            ))
    return out


def collect_registrations(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, kind, name)] where kind is "static" (a literal
    name) or "dynamic" (an f-string; `name` is its static prefix)."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in REGISTRY_METHODS
                and node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.append((rel, node.lineno, "static", arg.value))
            elif isinstance(arg, ast.JoinedStr):
                prefix = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant):
                        prefix += str(part.value)
                    else:
                        break
                out.append((rel, node.lineno, "dynamic", prefix))
    return out


def collect_label_uses(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, label_name, module_has_cap_helper)] for every
    explicit keyword on a metric write call (.inc/.set/.observe).

    `**spread` labels carry no static name and are skipped (none of the
    in-tree spreads feed unbounded sources; explicit keywords are the
    enforcement surface).  Whether the module references the cap helper
    (an import or a call of `capped_namespace_label`) is recorded per
    file so lint() can flag unbounded labels used outside it.
    """
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        has_helper = any(
            (isinstance(n, ast.Name) and n.id == CAP_HELPER)
            or (isinstance(n, ast.Attribute) and n.attr == CAP_HELPER)
            or (isinstance(n, ast.ImportFrom)
                and any(a.name == CAP_HELPER for a in n.names))
            for n in ast.walk(tree)
        )
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_WRITE_METHODS
                and node.keywords
            ):
                continue
            for kw in node.keywords:
                if kw.arg is None:  # **spread
                    continue
                out.append((rel, node.lineno, kw.arg, has_helper))
    return out


def _is_hot_path(rel: str) -> bool:
    p = "/" + rel.replace(os.sep, "/")
    return any("/" + prefix in p for prefix in HOT_PATH_PREFIXES)


def collect_broad_excepts(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, tagged)] for every `except Exception` / bare
    `except:` handler in a hot-path module.  `tagged` is whether the
    handler line (or the line above it — long rationales wrap) carries
    the `# chaos-ok:` tag."""

    def _catches_broad(h: ast.ExceptHandler) -> bool:
        if h.type is None:
            return True  # bare except
        names = (
            h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
        )
        # BaseException is in the net too: the strictly BROADER catch
        # must not be the easy way around the rationale requirement.
        return any(
            isinstance(n, ast.Name)
            and n.id in ("Exception", "BaseException")
            for n in names
        )

    out = []
    for rel, tree, lines in (
        trees if trees is not None else _parse_package(package_dir)
    ):
        if not _is_hot_path(rel):
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.ExceptHandler)
                    and _catches_broad(node)):
                continue
            nearby = lines[max(0, node.lineno - 2):node.lineno]
            out.append(
                (rel, node.lineno, any(CHAOS_OK_TAG in l for l in nearby))
            )
    return out


def collect_routed_paths(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, kind, path)] for every route in the shared
    observability handler: kind "exact" for `p == "/x"` comparisons,
    "prefix" for `p.startswith("/x/")`.  The bare "/" normalization
    compare is not a route and is skipped."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        if rel.replace(os.sep, "/") != EXPOSITION_REL.replace(os.sep, "/"):
            continue
        router = next(
            (n for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef) and n.name == ROUTER_FUNC),
            None,
        )
        if router is None:
            continue
        for node in ast.walk(router):
            if isinstance(node, ast.Compare):
                for side in [node.left, *node.comparators]:
                    if (
                        isinstance(side, ast.Constant)
                        and isinstance(side.value, str)
                        and side.value.startswith("/")
                        and side.value != "/"
                    ):
                        out.append((rel, node.lineno, "exact", side.value))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "startswith"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("/")
            ):
                out.append(
                    (rel, node.lineno, "prefix", node.args[0].value)
                )
    return out


def collect_fleet_routes(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, path)] for every string in the module-level
    `FLEET_ROUTES` tuple of trace/fleet.py — the paths the aggregator
    scrapes peers on and serves the merged view under."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        if rel.replace(os.sep, "/") != FLEET_REL.replace(os.sep, "/"):
            continue
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == FLEET_ROUTES_NAME
                    for t in node.targets
                )
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                continue
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    out.append((rel, node.lineno, elt.value))
    return out


def collect_rpc_context_mints(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, func, adopts)] for every `new_context(...)` /
    `use_context(...)` call in an rpc/ module.  `adopts` is whether the
    MODULE references adopt_context or adopt_or_new anywhere (import,
    name, or attribute) — minting a context on an inbound serving plane
    is only legitimate alongside the adoption path (adopt when the
    header is present, mint only as the no-header fallback)."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        if not rel.replace(os.sep, "/").startswith(RPC_PREFIX):
            continue
        adopts = any(
            (isinstance(n, ast.Name) and n.id in ADOPT_FUNCS)
            or (isinstance(n, ast.Attribute) and n.attr in ADOPT_FUNCS)
            or (isinstance(n, ast.ImportFrom)
                and any(a.name in ADOPT_FUNCS for a in n.names))
            for n in ast.walk(tree)
        )
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (
                func.id if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute)
                else None
            )
            if name in MINT_FUNCS:
                out.append((rel, node.lineno, name, adopts))
    return out


def collect_unledgered_jits(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno)] for the FIRST `jax.jit` use in each device-plane
    module (da/, kernels/, serve/, parallel/) that never references the
    device ledger.  One finding per module: the fix is registering the
    module's cache family, not annotating each jit site."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        p = rel.replace(os.sep, "/")
        if not any(
            p.startswith(f"celestia_app_tpu/{d}/")
            for d in ("da", "kernels", "serve", "parallel")
        ):
            continue
        jit_line = None
        references_ledger = False
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id == "jax"
            ):
                if jit_line is None:
                    jit_line = node.lineno
            elif (
                isinstance(node, ast.ImportFrom)
                and node.module is not None
                and node.module.endswith("device_ledger")
            ) or (
                isinstance(node, (ast.Name, ast.Attribute))
                and getattr(node, "id", getattr(node, "attr", None))
                == "device_ledger"
            ):
                references_ledger = True
        if jit_line is not None and not references_ledger:
            out.append((rel, jit_line))
    return out


def collect_unstitched_writes(package_dir: str = PACKAGE_DIR, trees=None):
    """[(file, lineno, table)] for every `.write(<table>, ...)` call
    whose table name resolves statically (string literal, or a Name
    bound to a module-level string constant) to something shaped like a
    trace table, but whose keywords carry neither `height=` nor
    `trace_id=` nor a `**splat` — and whose table is not in the
    height-free allowlist.

    The table-name regex is what separates tracer writes from the
    file/socket `.write(...)` calls that share the method name: a
    payload like "\\n" or a bytes body never matches
    `[a-z][a-z0-9_]*`."""
    out = []
    for rel, tree, _ in trees if trees is not None else _parse_package(package_dir):
        consts = {
            t.id: n.value.value
            for n in ast.walk(tree)
            if isinstance(n, ast.Assign)
            and isinstance(n.value, ast.Constant)
            and isinstance(n.value.value, str)
            for t in n.targets
            if isinstance(t, ast.Name)
        }
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "write"
                and node.args
            ):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                table = arg.value
            elif isinstance(arg, ast.Name) and arg.id in consts:
                table = consts[arg.id]
            else:
                continue  # self.TABLE / locals: not statically resolvable
            if not TABLE_NAME_RE.match(table):
                continue  # a file payload, not a trace table name
            if table in HEIGHT_FREE_TABLES:
                continue
            stamped = any(
                kw.arg is None or kw.arg in STITCH_KEYS
                for kw in node.keywords
            )
            if not stamped:
                out.append((rel, node.lineno, table))
    return out


def readme_metric_tokens(readme_path: str = README) -> set[str]:
    with open(readme_path, encoding="utf-8") as f:
        return set(README_TOKEN_RE.findall(f.read()))


def readme_endpoint_paths(readme_path: str = README) -> set[str]:
    """Every `GET /path` the README documents (the endpoint table plus
    any prose mention — either keeps the route discoverable)."""
    with open(readme_path, encoding="utf-8") as f:
        return set(README_ENDPOINT_RE.findall(f.read()))


def lint(package_dir: str = PACKAGE_DIR, readme_path: str = README) -> list[str]:
    problems = []
    trees = _parse_package(package_dir)  # one walk feeds both collectors
    tokens = readme_metric_tokens(readme_path)
    # A documented dynamic family like celestia_block_<stage>_seconds
    # covers every name matching it with the placeholder as one
    # [a-z0-9_]+ segment — prefix AND suffix must line up (prefix-only
    # matching let `celestia_<span>_seconds` whitelist every name).
    doc_res = [
        re.compile("^" + re.sub(r"<[a-z0-9_]+>", "[a-z0-9_]+", t) + "$")
        for t in tokens if "<" in t
    ]
    for rel, lineno, kind, name in collect_registrations(package_dir, trees):
        where = f"{rel}:{lineno}"
        if kind == "static":
            if not METRIC_NAME_RE.match(name):
                problems.append(
                    f"{where}: metric {name!r} does not match "
                    "celestia_[a-z0-9_]+"
                )
            elif name not in tokens and not any(
                r.match(name) for r in doc_res
            ):
                problems.append(
                    f"{where}: metric {name!r} missing from the README "
                    "metrics table"
                )
        else:
            if not METRIC_PREFIX_RE.match(name):
                problems.append(
                    f"{where}: dynamic metric prefix {name!r} does not "
                    "match celestia_[a-z0-9_]*"
                )
            elif not any(t.startswith(name) for t in tokens):
                problems.append(
                    f"{where}: dynamic metric family {name!r}* missing "
                    "from the README metrics table"
                )
    for rel, lineno, label, has_helper in collect_label_uses(package_dir, trees):
        where = f"{rel}:{lineno}"
        if not LABEL_NAME_RE.match(label):
            problems.append(
                f"{where}: metric label {label!r} does not match "
                "[a-z][a-z0-9_]*"
            )
        elif label in UNBOUNDED_LABELS and not has_helper:
            problems.append(
                f"{where}: label {label!r} is unbounded-cardinality; route "
                f"the value through trace/square_journal.{CAP_HELPER} "
                "(module never references the helper)"
            )
    for rel, lineno, tagged in collect_broad_excepts(package_dir, trees):
        if not tagged:
            problems.append(
                f"{rel}:{lineno}: broad `except Exception` in a hot-path "
                f"module without a `# {CHAOS_OK_TAG}` rationale — swallow "
                "sites on the block path must say why they are not a "
                "retry/degrade/propagate seam (see chaos/)"
            )
    endpoints = readme_endpoint_paths(readme_path)
    for rel, lineno, kind, path in collect_routed_paths(package_dir, trees):
        where = f"{rel}:{lineno}"
        if kind == "exact":
            documented = path in endpoints
        else:  # prefix route: any documented path under the prefix counts
            documented = any(
                e.startswith(path) and len(e) > len(path) for e in endpoints
            )
        if not documented:
            problems.append(
                f"{where}: routed path {path!r}{'*' if kind == 'prefix' else ''} "
                "missing from the README endpoint table — every route on "
                "the shared observability handler must be documented "
                "(GET <path> in README.md)"
            )
    for rel, lineno, path in collect_fleet_routes(package_dir, trees):
        if path not in endpoints:
            problems.append(
                f"{rel}:{lineno}: fleet route {path!r} missing from the "
                "README endpoint table — every FLEET_ROUTES path must be "
                "documented (GET <path> in README.md)"
            )
    for rel, lineno, func, adopts in collect_rpc_context_mints(
        package_dir, trees
    ):
        if not adopts:
            problems.append(
                f"{rel}:{lineno}: rpc module calls {func}() but never "
                "references adopt_context/adopt_or_new — an inbound "
                "serving plane that mints instead of adopting the "
                "x-celestia-trace header splits the cross-node trace"
            )
    for rel, lineno in collect_unledgered_jits(package_dir, trees):
        problems.append(
            f"{rel}:{lineno}: module builds jit programs but never "
            "references trace/device_ledger — register the cache family "
            "(device_ledger.track) so GET /device can attribute its "
            "compiles, dispatches, and residency"
        )
    for rel, lineno, table in collect_unstitched_writes(package_dir, trees):
        problems.append(
            f"{rel}:{lineno}: trace table {table!r} written without "
            "height= or trace_id= — the height-anatomy timeline "
            "(trace/timeline.py) cannot stitch an unstamped row; stamp "
            "it, or add the table to HEIGHT_FREE_TABLES if it genuinely "
            "belongs to no height"
        )
    return problems


def main() -> int:
    problems = lint()
    regs = collect_registrations()
    routes = collect_routed_paths()
    print(
        f"trace_lint: {len(regs)} registrations "
        f"({len({n for _, _, k, n in regs if k == 'static'})} distinct static names), "
        f"{len(routes)} observability routes"
    )
    for p in problems:
        print(f"  PROBLEM {p}")
    if problems:
        return 1
    print("trace_lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Metric-name lint: registry names must be well-formed AND documented.

Walks every registry().counter/gauge/histogram registration in
`celestia_app_tpu/` (AST, no imports — runs in any image) and checks:

  1. the name matches `celestia_[a-z0-9_]+` (static names exactly;
     f-string names on their static prefix), so the exposition namespace
     stays uniform; and
  2. the name appears in the README "Metrics" table (dynamic families may
     be documented with a `<placeholder>` segment, e.g.
     `celestia_block_<stage>_seconds`, matched by prefix), so docs and
     exposition goldens cannot drift apart.

Run standalone (exit 1 on problems) or via tests/test_trace_lint.py,
which puts the check in tier-1.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "celestia_app_tpu")
README = os.path.join(REPO_ROOT, "README.md")

METRIC_NAME_RE = re.compile(r"^celestia_[a-z0-9_]+$")
METRIC_PREFIX_RE = re.compile(r"^celestia_[a-z0-9_]*$")
README_TOKEN_RE = re.compile(r"celestia_[a-z0-9_<>]+")
REGISTRY_METHODS = {"counter", "gauge", "histogram"}


def collect_registrations(package_dir: str = PACKAGE_DIR):
    """[(file, lineno, kind, name)] where kind is "static" (a literal
    name) or "dynamic" (an f-string; `name` is its static prefix)."""
    out = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
            rel = os.path.relpath(path, REPO_ROOT)
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in REGISTRY_METHODS
                    and node.args
                ):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    out.append((rel, node.lineno, "static", arg.value))
                elif isinstance(arg, ast.JoinedStr):
                    prefix = ""
                    for part in arg.values:
                        if isinstance(part, ast.Constant):
                            prefix += str(part.value)
                        else:
                            break
                    out.append((rel, node.lineno, "dynamic", prefix))
    return out


def readme_metric_tokens(readme_path: str = README) -> set[str]:
    with open(readme_path, encoding="utf-8") as f:
        return set(README_TOKEN_RE.findall(f.read()))


def lint(package_dir: str = PACKAGE_DIR, readme_path: str = README) -> list[str]:
    problems = []
    tokens = readme_metric_tokens(readme_path)
    # A documented dynamic family like celestia_block_<stage>_seconds
    # covers every name sharing its static prefix.
    doc_prefixes = [t.split("<", 1)[0] for t in tokens if "<" in t]
    for rel, lineno, kind, name in collect_registrations(package_dir):
        where = f"{rel}:{lineno}"
        if kind == "static":
            if not METRIC_NAME_RE.match(name):
                problems.append(
                    f"{where}: metric {name!r} does not match "
                    "celestia_[a-z0-9_]+"
                )
            elif name not in tokens and not any(
                p and name.startswith(p) for p in doc_prefixes
            ):
                problems.append(
                    f"{where}: metric {name!r} missing from the README "
                    "metrics table"
                )
        else:
            if not METRIC_PREFIX_RE.match(name):
                problems.append(
                    f"{where}: dynamic metric prefix {name!r} does not "
                    "match celestia_[a-z0-9_]*"
                )
            elif not any(t.startswith(name) for t in tokens):
                problems.append(
                    f"{where}: dynamic metric family {name!r}* missing "
                    "from the README metrics table"
                )
    return problems


def main() -> int:
    problems = lint()
    regs = collect_registrations()
    print(
        f"trace_lint: {len(regs)} registrations "
        f"({len({n for _, _, k, n in regs if k == 'static'})} distinct static names)"
    )
    for p in problems:
        print(f"  PROBLEM {p}")
    if problems:
        return 1
    print("trace_lint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

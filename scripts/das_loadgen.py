#!/usr/bin/env python
"""DAS sampling load generator: thousands of queued share samples, p50/p99.

The txsim of the read side.  Where txsim floods BroadcastTx, this floods
the proof plane: N worker threads draw seeded-random (height, row, col)
coordinates over M cached squares and push them through the batched
ProofSampler queue (serve/sampler.py) — exactly the path the three RPC
planes serve — measuring per-sample wall latency and aggregate
proofs/sec.  A seeded subset of proofs is verified against the committed
DAH data root, so a loadgen run that "performs well" while serving
garbage fails loudly.

Runs crypto-free (no signing stack): squares are deterministic synthetic
blocks admitted straight into a ForestCache, so the tool measures the
serve plane, not block production.  `--mode host` drives the pure-host
fallback for an A/B number; `--url` instead samples a LIVE node's
GET /das/share_proof endpoint over HTTP.

  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/das_loadgen.py \
      --heights 4 --k 16 --samples 2000 --threads 8 \
      --metrics-out /tmp/das --round-out DAS_r01.json

SWARM mode (`--clients N`, N >= 1): instead of a few closed-loop
threads, the run simulates a light-client SWARM — 10^4..10^6 clients,
each bound to a tenant namespace by zipf popularity (`--zipf-a`),
arriving OPEN-LOOP as a Poisson process at `--rate` samples/sec (an
arrival is enqueued at its scheduled instant whether or not the plane
has caught up, so latency includes queue delay — the honest saturation
measurement a closed loop cannot make).  Heights skew hot
(`--hot-frac` on the newest height) with a cache-busting historical
tail (`--historical-frac` hits heights beyond retention, forcing the
rebuild path), and coordinates mix tenant-targeted reads with uniform
DAS sampling.  `--shard-sweep 1,8` re-runs the identical plan per
$CELESTIA_SERVE_SHARDS setting (serve/shard.py) so the proofs/sec
scaling curve lands in one DAS_rNN round, per shard count, next to
per-tenant p50/p99/SLO-burn columns (`--slo-ms`, 99% objective):

  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/das_loadgen.py \
      --clients 20000 --tenants 8 --rate 300 --samples 2400 --k 16 \
      --shard-sweep 1,8 --round-out DAS_r02.json

QOS mode (`--qos-out QOS_rNN.json`, needs --clients): two swarm legs
under one $CELESTIA_QOS policy over the IDENTICAL honest plan —
`baseline` (no spammer) then `spam` (a spammer tenant firing
tenant-targeted reads at `--spam-mult` x its `--proof-rate-limit`).
Throttled samples are POLICY, not failures: they land in their own
per-tenant column and burn no SLO budget.  scripts/bench_trend.py
validates the round shape (malformed exits 2) and gates the
enforcement invariants: spammer throttled, honest tenants' p99 and
SLO burn no worse than the no-spammer leg.

Prints a one-line JSON summary; --metrics-out writes das_loadgen.prom
(the celestia_proof_* / celestia_serve_* families) + das_loadgen.jsonl;
--round-out writes the DAS_rNN.json record scripts/bench_trend.py reads
into its proofs/sec + proof-p99 trend series and regression gate (swarm
rounds carry schema "das-v2": workload, sweep rows, tenant columns).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402


def deterministic_square(k: int, seed: int):
    """One synthetic namespace-ordered ODS (the chaos_soak block shape)."""
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    ns = np.sort(rng.integers(0, 128, k * k).astype(np.uint8))
    ods = rng.integers(0, 256, (k * k, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    return ods.reshape(k, k, SHARE_SIZE)


def build_cache(heights: int, k: int, seed: int):
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.serve.cache import ForestCache

    cache = ForestCache(heights=heights, spill=heights)
    roots = {}
    for h in range(1, heights + 1):
        eds = ExtendedDataSquare.compute(deterministic_square(k, seed + h))
        cache.put(h, eds)
        roots[h] = eds.data_root()
    return cache, roots


def _run_plan(sampler, cache, plan, threads, verify_every, roots):
    """One threaded pass over the sampling plan; returns
    (lat_ms sorted, failures, withheld [(height, row, col)], wall_s).
    A ShareWithheld is NOT a failure — it is the adversarial 410 path
    the run exists to exercise — and it never kills a worker."""
    from celestia_app_tpu.serve.sampler import ShareWithheld
    from celestia_app_tpu.serve.verify import verify_share_proof

    latencies: list[float] = []
    failures: list[str] = []
    withheld: list[tuple[int, int, int]] = []
    lock = threading.Lock()
    cursor = iter(range(len(plan)))

    def worker():
        while True:
            with lock:
                i = next(cursor, None)
            if i is None:
                return
            h, r, c, axis = plan[i]
            entry, _ = cache.get(h)
            t0 = time.perf_counter()
            try:
                proof = sampler.share_proof(entry, r, c, axis=axis)
            except ShareWithheld:
                with lock:
                    withheld.append((h, r, c))
                continue
            except Exception as e:  # noqa: BLE001 — a drop IS the measurement
                with lock:
                    failures.append(f"({h},{r},{c}): {type(e).__name__}: {e}")
                return
            dt = time.perf_counter() - t0
            ok = True
            if i % verify_every == 0:
                # The client-side check rides the batched verifier
                # (serve/verify.py — host fallback bit-identical), the
                # same program a light-client fleet amortizes queues
                # through.
                ok = verify_share_proof(proof, roots[h])
            with lock:
                latencies.append(dt)
                if not ok:
                    failures.append(f"({h},{r},{c}): proof failed verify")

    t_start = time.perf_counter()
    workers = [
        threading.Thread(target=worker, daemon=True) for _ in range(threads)
    ]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    wall_s = time.perf_counter() - t_start
    return sorted(v * 1e3 for v in latencies), failures, withheld, wall_s


def _percentile(sorted_ms, p):
    """Nearest-rank percentile over an ascending ms list — the ONE
    formula every round record (closed-loop and swarm) feeds into
    bench_trend, so the two workloads can never drift apart."""
    if not sorted_ms:
        return None
    return round(sorted_ms[min(len(sorted_ms) - 1, int(p * len(sorted_ms)))], 3)


def _pass_stats(lat_ms, wall_s) -> dict:
    return {
        "samples": len(lat_ms),
        "wall_s": round(wall_s, 3),
        "proofs_per_s": round(len(lat_ms) / wall_s, 2) if wall_s else None,
        "proof_p50_ms": _percentile(lat_ms, 0.50),
        "proof_p99_ms": _percentile(lat_ms, 0.99),
    }


def run_local(args) -> dict:
    """Drive the in-process sampler queue with `threads` workers.

    With `--withhold-frac` the run becomes ADVERSARIAL: a withholding
    proposer (chaos/adversary.py, seeded by `--adv-seed`) hides that
    fraction of every height's shares, so workers exercise the 410
    detection path under load.  With `--heal` on top, every detected
    height is healed (serve/heal.py: gather survivors -> batched repair
    -> root-verify -> re-admit) and the SAME plan re-runs post-heal —
    the summary then reports pre-heal vs post-heal proofs/sec and the
    time from heal trigger to the first healed proof served."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.serve.sampler import ProofSampler

    adversarial = args.withhold_frac > 0
    if adversarial:
        chaos.install(
            f"seed={args.adv_seed},withhold_frac={args.withhold_frac}"
        )
    try:
        cache, roots = build_cache(args.heights, args.k, args.seed)
        sampler = ProofSampler()
        n = 2 * args.k
        rng = np.random.default_rng(args.seed)
        axes = (
            ("row", "col") if args.axes == "both" else (args.axes,)
        )
        plan = [
            (int(rng.integers(1, args.heights + 1)),
             int(rng.integers(0, n)), int(rng.integers(0, n)),
             axes[int(rng.integers(0, len(axes)))])
            for _ in range(args.samples)
        ]
        verify_every = max(1, args.samples // max(args.verify, 1))
        # Warm the serve AND verify programs off the clock (the swarm
        # leg's gather-warm pattern): the first batched verify pays the
        # jit compile — seconds on CPU — which must not land inside the
        # measured pass.  One bucket covers both axes at a fixed k.
        try:
            from celestia_app_tpu.serve.verify import verify_share_proof

            entry, _ = cache.get(1)
            warm = sampler.sample_batch(entry, [(0, 0)])
            verify_share_proof(warm[0], roots[1])
        except Exception:  # noqa: BLE001 — warmup only (withheld (0,0) etc.)
            pass
        lat_ms, failures, withheld, wall_s = _run_plan(
            sampler, cache, plan, args.threads, verify_every, roots
        )

        heal_block = None
        if args.heal and withheld:
            from celestia_app_tpu.serve.api import DasProvider
            from celestia_app_tpu.serve.heal import HealingEngine

            provider = DasProvider(cache=cache, sampler=sampler)
            engine = HealingEngine(provider, name="loadgen")
            t_heal0 = time.perf_counter()
            hit_heights = sorted({h for h, _, _ in withheld})
            for h in hit_heights:
                engine.note("withheld", h)
            outcomes = dict(engine.process_pending())
            # Time to FIRST healed proof: the earliest previously-
            # withheld coordinate that now serves a verifying proof.
            first_healed_ms = None
            for h, r, c in withheld:
                if outcomes.get(h) != "healed":
                    continue
                proof = sampler.share_proof(provider.entry(h), r, c)
                if proof.verify(roots[h]):
                    first_healed_ms = round(
                        (time.perf_counter() - t_heal0) * 1e3, 3
                    )
                break
            post_lat, post_fail, post_withheld, post_wall = _run_plan(
                sampler, cache, plan, args.threads, verify_every, roots
            )
            failures.extend(post_fail)
            engine.close()
            heal_block = {
                "heights_healed": [
                    h for h in hit_heights if outcomes.get(h) == "healed"
                ],
                "outcomes": {str(h): o for h, o in outcomes.items()},
                "time_to_first_healed_proof_ms": first_healed_ms,
                "post_heal": _pass_stats(post_lat, post_wall),
                "post_heal_withheld_hits": len(post_withheld),
            }
    finally:
        if adversarial:
            chaos.uninstall()

    import jax

    summary = {
        "metric": "das_loadgen",
        "mode": os.environ.get("CELESTIA_SERVE_MODE", "") or "batched",
        "requested": args.samples,
        "heights": args.heights,
        "k": args.k,
        "threads": args.threads,
        "axes": args.axes,
        **_pass_stats(lat_ms, wall_s),
        "verified": (len(lat_ms) + verify_every - 1) // verify_every,
        "failures": failures[:5],
        "platform": jax.default_backend(),
        "cache": cache.stats(),
    }
    if adversarial:
        summary["withhold_frac"] = args.withhold_frac
        summary["adv_seed"] = args.adv_seed
        summary["withheld_hits"] = len(withheld)
    if heal_block is not None:
        summary["heal"] = heal_block
    return summary


# --- the swarm harness (open-loop light-client fleet) ------------------------

def tenant_square(k: int, seed: int, tenants: int):
    """One synthetic namespace-ordered ODS with exactly `tenants`
    namespaces; returns (ods, ranges) where ranges[t] = (start, end)
    share-index range of tenant t (contiguous — the square is
    namespace-sorted, like every real square)."""
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    if not 1 <= tenants <= 255:
        # Tenant ids map onto one namespace byte (value 1..255; 0 stays
        # reserved) — more would silently wrap uint8 and alias tenants.
        raise ValueError(f"tenants must be 1..255, got {tenants}")
    rng = np.random.default_rng(seed)
    n = k * k
    vals = np.sort(rng.integers(0, tenants, n).astype(np.uint8))
    ods = rng.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = vals + 1  # 1..tenants; 0 stays reserved
    ranges = {}
    for t in range(tenants):
        idx = np.nonzero(vals == t)[0]
        if len(idx):
            ranges[int(t)] = (int(idx[0]), int(idx[-1]) + 1)
    return ods.reshape(k, k, SHARE_SIZE), ranges


def build_swarm_plan(args, squares, client_tenant):
    """The deterministic arrival schedule every sweep leg replays:
    [(t_arrival_s, client, tenant, height, row, col, axis), ...].

    Poisson arrivals at --rate; height mix: --hot-frac on the newest
    retained height, --historical-frac on beyond-retention heights (the
    cache-busting rebuild path), the rest uniform over the retained
    tail; coordinates: 3/4 inside the client's tenant namespace range
    (the tenant-targeted read), 1/4 uniform over the full EDS (the DAS
    mix, parity quadrants included)."""
    rng = np.random.default_rng(args.seed + 7)
    k, n = args.k, 2 * args.k
    hot_h = args.heights
    plan = []
    t = 0.0
    for _ in range(args.samples):
        t += float(rng.exponential(1.0 / args.rate))
        client = int(rng.integers(0, args.clients))
        tenant = int(client_tenant[client])
        u = rng.random()
        if u < args.hot_frac:
            height = hot_h
        elif u < args.hot_frac + args.historical_frac and args.historical:
            height = hot_h + 1 + int(rng.integers(0, args.historical))
        else:
            height = 1 + int(rng.integers(0, hot_h))
        ranges = squares[height][1]
        if rng.random() < 0.75 and tenant in ranges:
            start, end = ranges[tenant]
            share = start + int(rng.integers(0, end - start))
            row, col = share // k, share % k
        else:
            row, col = int(rng.integers(0, n)), int(rng.integers(0, n))
        axis = "col" if rng.random() < 0.5 else "row"
        plan.append((t, client, tenant, height, row, col, axis))
    return plan


_THROTTLED = "__throttled__"  # the worker's QosThrottled sentinel


def _tenant_stats(results, slo_ms: float) -> dict:
    """Per-tenant p50/p99 + SLO burn (99% of samples under --slo-ms;
    burn = violation fraction / the 1% error budget, so burn > 1 means
    the tenant is eating budget faster than the objective allows).
    A FAILED sample is a violation too — a tenant whose requests mostly
    error must burn budget, not report a rosy number built from its few
    fast successes (percentiles still cover served samples only; the
    `failed` column carries the drop count).  A THROTTLED sample
    ($CELESTIA_QOS proof-rate refusal) is POLICY, not failure: it lands
    in its own column and burns no SLO budget — the spammer being over
    its limit is the enforcement working, and honest tenants are never
    throttled in a correctly-sized policy."""
    served: dict[int, list[float]] = {}
    failed: dict[int, int] = {}
    throttled: dict[int, int] = {}
    for tenant, lat_s, err in results:
        if err is None:
            served.setdefault(tenant, []).append(lat_s * 1e3)
        elif err == _THROTTLED:
            throttled[tenant] = throttled.get(tenant, 0) + 1
        else:
            failed[tenant] = failed.get(tenant, 0) + 1
    out = {}
    for tenant in sorted(set(served) | set(failed) | set(throttled)):
        lats = sorted(served.get(tenant, []))
        drops = failed.get(tenant, 0)
        total = len(lats) + drops
        over = sum(1 for v in lats if v > slo_ms) + drops
        out[f"t{tenant:02d}"] = {
            "samples": len(lats),
            "served": len(lats),
            "failed": drops,
            "throttled": throttled.get(tenant, 0),
            "p50_ms": _percentile(lats, 0.50),
            "p99_ms": _percentile(lats, 0.99),
            "slo_burn": (
                round((over / total) / 0.01, 3) if total else 0.0
            ),
        }
    return out


def _run_swarm_leg(args, shards: int, squares, plan, eds_by_height
                   ) -> tuple[dict, list]:
    """One shard-count leg: identical plan, fresh cache admitted under
    $CELESTIA_SERVE_SHARDS=<shards>, open-loop replay.  Returns the leg
    summary (whose "shards" is the count the plane ACTUALLY ran with —
    serve_shards clamps to the device count) + raw results."""
    import queue

    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.serve.api import DasProvider
    from celestia_app_tpu.serve.cache import ForestCache
    from celestia_app_tpu.serve.sampler import ProofSampler

    roots = {h: eds.data_root() for h, eds in eds_by_height.items()}

    def leg_handle(h: int) -> ExtendedDataSquare:
        """A fresh per-leg handle over the shared device buffer: legs
        must not share the MUTABLE handle, because an earlier leg's
        spill converts eds._eds to numpy IN PLACE and a later leg would
        then serve that height's shares from host memory — biasing the
        very scaling curve the sweep measures.  The device buffer
        itself is read-only and shared; only the handle state (spill
        tier, forest attachment, tree memo) is per leg."""
        base = eds_by_height[h]
        return ExtendedDataSquare(
            base._eds, list(base.row_roots()), list(base.col_roots()),
            base.data_root(), base.k,
        )

    saved = os.environ.get("CELESTIA_SERVE_SHARDS")
    os.environ["CELESTIA_SERVE_SHARDS"] = str(shards)
    try:
        cache = ForestCache(heights=args.heights, spill=args.heights)
        rebuild = lambda h: (  # noqa: E731 — the cache-busting path
            ExtendedDataSquare.compute(squares[h][0])
            if h in squares else None
        )
        provider = DasProvider(cache=cache, rebuild=rebuild)
        sampler = provider.sampler
        for h in range(1, args.heights + 1):
            # One extension per height for the whole sweep; historical
            # rebuilds still pay the full recompute — that cost is the
            # point of the tail.
            cache.put(h, leg_handle(h))
        # Warm the gather programs (sharded or not) off the clock: the
        # sharded program is compiled per pow-2 slot bucket, so warm
        # every bucket a realistic micro-batch can land on.
        entry, _ = cache.get(args.heights)
        warm_proofs = sampler.sample_batch(entry, [(0, 0), (1, 1)])
        # Verify-program warmup rides the same off-the-clock window: the
        # workers' batched client-side check must never pay the compile
        # inside the open-loop pass.
        try:
            from celestia_app_tpu.serve.verify import verify_share_proof

            verify_share_proof(warm_proofs[0], roots[args.heights])
        except Exception:  # noqa: BLE001 — warmup only
            pass
        # The shard count the plane ACTUALLY admitted under (serve_shards
        # clamps to the device count): sweep rows must record the mesh
        # that ran, or bench_trend gates the wrong scaling-curve series.
        shards = getattr(entry, "shards", 0) or 1
        if shards > 1 and hasattr(entry, "_sharded_gather"):
            for b in (1, 2, 4, 8, 16, 32, 64, 128):
                entry.gather("row", list(range(min(b, entry.forest_rows))))

        q: queue.Queue = queue.Queue()
        results: list[tuple[int, float, str | None]] = []
        lock = threading.Lock()
        verify_every = max(1, args.samples // max(args.verify, 1))
        t0 = time.perf_counter()

        def producer():
            for i, item in enumerate(plan):
                delay = item[0] - (time.perf_counter() - t0)
                if delay > 0:
                    time.sleep(delay)
                q.put((i, item))
            for _ in range(args.threads):
                q.put(None)

        from celestia_app_tpu.qos import QosThrottled
        from celestia_app_tpu.serve.verify import verify_share_proof

        def worker():
            while True:
                got = q.get()
                if got is None:
                    return
                i, (t_sched, _client, tenant, h, r, c, axis) = got
                err = None
                try:
                    entry = provider.entry(h)
                    proof = sampler.share_proof(entry, r, c, axis=axis)
                    if (i % verify_every == 0
                            and not verify_share_proof(proof, roots[h])):
                        err = "proof failed verify"
                except QosThrottled:
                    # The 429 path: a refusal is the ENFORCEMENT being
                    # measured, never a drop (and never a dead worker).
                    err = _THROTTLED
                except Exception as e:  # noqa: BLE001 — a drop IS the measurement
                    err = f"({h},{r},{c}): {type(e).__name__}: {e}"
                lat = (time.perf_counter() - t0) - t_sched
                with lock:
                    results.append((tenant, lat, err))

        threads = [threading.Thread(target=producer, daemon=True)] + [
            threading.Thread(target=worker, daemon=True)
            for _ in range(args.threads)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall_s = time.perf_counter() - t0
        served = sorted(
            lat * 1e3 for _, lat, err in results if err is None
        )
        failures = [
            err for _, _, err in results
            if err is not None and err != _THROTTLED
        ]
        throttled = sum(1 for _, _, err in results if err == _THROTTLED)
        leg = {
            "shards": shards,
            "throttled": throttled,
            "samples": len(served),
            "wall_s": round(wall_s, 3),
            "offered_rate": args.rate,
            "proofs_per_s": (
                round(len(served) / wall_s, 2) if wall_s else None
            ),
            "proof_p50_ms": _percentile(served, 0.50),
            "proof_p99_ms": _percentile(served, 0.99),
            "failures": failures[:5],
            "cache": cache.stats(),
        }
        return leg, results
    finally:
        if saved is None:
            os.environ.pop("CELESTIA_SERVE_SHARDS", None)
        else:
            os.environ["CELESTIA_SERVE_SHARDS"] = saved


def run_swarm(args) -> dict:
    """The light-client swarm: one deterministic open-loop plan replayed
    per --shard-sweep leg, so the shard-count scaling curve is measured
    on an identical workload."""
    from celestia_app_tpu.da.eds import ExtendedDataSquare

    import jax

    # Dedup the sweep on the counts the plane will ACTUALLY run with
    # (serve_shards clamps to the device count): `--shard-sweep 1,8,16`
    # on an 8-device host must not spend a whole open-loop leg on a
    # second 8-shard run only for its rows to overwrite the first's.
    have = len(jax.devices())
    sweep = sorted({
        min(int(s), have) if int(s) > 1 else 1
        for s in str(args.shard_sweep).split(",") if s.strip()
    }) or [1]
    total_heights = args.heights + args.historical
    squares = {
        h: tenant_square(args.k, args.seed + h, args.tenants)
        for h in range(1, total_heights + 1)
    }
    # One extension per height, shared by every leg (bit-identical
    # squares; only the historical rebuild path recomputes, on purpose).
    eds_by_height = {
        h: ExtendedDataSquare.compute(squares[h][0])
        for h in range(1, total_heights + 1)
    }
    crng = np.random.default_rng(args.seed)
    # Zipf over exactly the tenant set (p(rank) ~ rank^-a, tenant 0 the
    # most popular): clipping an unbounded zipf draw would pile the
    # whole tail onto the LAST tenant and invert the skew.
    ranks = np.arange(1, args.tenants + 1, dtype=np.float64)
    popularity = ranks ** -args.zipf_a
    popularity /= popularity.sum()
    client_tenant = crng.choice(args.tenants, size=args.clients, p=popularity)
    plan = build_swarm_plan(args, squares, client_tenant)

    legs, tenant_blocks = [], {}
    for shards in sweep:
        leg, results = _run_swarm_leg(
            args, shards, squares, plan, eds_by_height
        )
        legs.append(leg)
        # Keyed by the ACTUAL shard count the leg ran with (clamping
        # may fold a requested count onto a narrower mesh) — the
        # primary lookup below uses the same key.
        tenant_blocks[leg["shards"]] = _tenant_stats(results, args.slo_ms)

    import jax

    primary = legs[-1]  # the widest mesh is the round's headline leg
    return {
        "metric": "das_swarm",
        "workload": "swarm",
        "mode": os.environ.get("CELESTIA_SERVE_MODE", "") or "batched",
        "clients": args.clients,
        "tenants": args.tenants,
        "zipf_a": args.zipf_a,
        "arrival": "poisson",
        "rate": args.rate,
        "hot_frac": args.hot_frac,
        "historical_frac": args.historical_frac,
        "requested": args.samples,
        "heights": args.heights,
        "historical": args.historical,
        "k": args.k,
        "threads": args.threads,
        "slo_ms": args.slo_ms,
        "samples": primary["samples"],
        "wall_s": primary["wall_s"],
        "proofs_per_s": primary["proofs_per_s"],
        "proof_p50_ms": primary["proof_p50_ms"],
        "proof_p99_ms": primary["proof_p99_ms"],
        "headline_shards": primary["shards"],
        "sweep": legs,
        "tenant_stats": tenant_blocks[primary["shards"]],
        "failures": [f for leg in legs for f in leg["failures"]][:5],
        "platform": jax.default_backend(),
    }


# --- the QoS enforcement run (whale + small tenants + spammer) ---------------

def run_qos(args) -> dict:
    """Two swarm legs under one $CELESTIA_QOS policy, identical honest
    plan: `baseline` (no spammer) then `spam` (a spammer tenant firing
    tenant-targeted reads at --spam-mult x its per-tenant proof-rate
    limit).  The record (schema qos-v1, QOS_rNN.json via --qos-out) is
    what bench_trend gates: spammer throttled, every honest tenant's
    p99/SLO burn no worse than its no-spammer leg."""
    from celestia_app_tpu import qos
    from celestia_app_tpu.da.eds import ExtendedDataSquare

    import jax

    if args.clients <= 0:
        raise SystemExit("--qos-out needs swarm mode (--clients N)")
    if args.tenants < 3:
        raise SystemExit("--qos-out needs >= 3 tenants (whale+small+spam)")
    spam_t = (
        args.tenants - 1 if args.spam_tenant is None else args.spam_tenant
    )
    # tenant_square writes tenant t as namespace byte t+1; the serve
    # plane's capped label is the hex with leading zeros stripped —
    # the SAME label the sampler charges, so the policy binds exactly
    # the spammer's reads.
    spam_label = format(spam_t + 1, "x")
    limit = args.proof_rate_limit
    total_heights = args.heights + args.historical
    squares = {
        h: tenant_square(args.k, args.seed + h, args.tenants)
        for h in range(1, total_heights + 1)
    }
    eds_by_height = {
        h: ExtendedDataSquare.compute(squares[h][0])
        for h in range(1, total_heights + 1)
    }
    crng = np.random.default_rng(args.seed)
    honest_ids = [t for t in range(args.tenants) if t != spam_t]
    honest = len(honest_ids)
    ranks = np.arange(1, len(honest_ids) + 1, dtype=np.float64)
    popularity = ranks ** -args.zipf_a
    popularity /= popularity.sum()
    client_tenant = crng.choice(
        np.array(honest_ids), size=args.clients, p=popularity
    )
    plan = build_swarm_plan(args, squares, client_tenant)
    # The spammer: open-loop Poisson at spam_mult x its limit, every
    # arrival a tenant-targeted read inside its own namespace range on
    # the hot height (the read the proof-rate bucket charges).
    srng = np.random.default_rng(args.seed + 99)
    spam_rate = args.spam_mult * limit
    duration = plan[-1][0] if plan else 1.0
    k, hot_h = args.k, args.heights
    spam_plan = []
    t = float(srng.exponential(1.0 / spam_rate))
    while t < duration:
        ranges = squares[hot_h][1]
        start, end = ranges.get(spam_t, (0, 1))
        share = start + int(srng.integers(0, max(end - start, 1)))
        spam_plan.append((
            t, -1, spam_t, hot_h, share // k, share % k,
            "col" if srng.random() < 0.5 else "row",
        ))
        t += float(srng.exponential(1.0 / spam_rate))
    merged = sorted(plan + spam_plan)

    qos.install(
        f"{spam_label}.proof_rate={limit},{spam_label}.proof_burst={limit}"
    )
    try:
        # A discarded warm leg pays the gather-program compiles: the
        # baseline-vs-spam comparison must measure the POLICY, not which
        # leg ran first against a cold jit cache.
        _run_swarm_leg(
            args, 1, squares, plan[:min(60, len(plan))], eds_by_height
        )
        base_leg, base_results = _run_swarm_leg(
            args, 1, squares, plan, eds_by_height
        )
        spam_leg, spam_results = _run_swarm_leg(
            args, 1, squares, merged, eds_by_height
        )
    finally:
        qos.uninstall()
    tenants_base = _tenant_stats(base_results, args.slo_ms)
    tenants_spam = _tenant_stats(spam_results, args.slo_ms)
    spam_key = f"t{spam_t:02d}"
    return {
        "metric": "das_qos",
        "schema": "qos-v1",
        "workload": "qos",
        "clients": args.clients,
        "tenants": args.tenants,
        "honest_tenants": honest,
        "spam_tenant": spam_key,
        "spam_namespace": spam_label,
        "proof_rate_limit": limit,
        "spam_mult": args.spam_mult,
        "rate": args.rate,
        "slo_ms": args.slo_ms,
        "k": args.k,
        "heights": args.heights,
        "samples": base_leg["samples"],
        "spam_arrivals": len(spam_plan),
        "legs": {
            "baseline": {**{k_: base_leg[k_] for k_ in (
                "samples", "wall_s", "proofs_per_s", "proof_p50_ms",
                "proof_p99_ms", "throttled")}, "tenants": tenants_base},
            "spam": {**{k_: spam_leg[k_] for k_ in (
                "samples", "wall_s", "proofs_per_s", "proof_p50_ms",
                "proof_p99_ms", "throttled")}, "tenants": tenants_spam},
        },
        "failures": (base_leg["failures"] + spam_leg["failures"])[:5],
        "platform": jax.default_backend(),
    }


def attest_verify_block(args) -> dict:
    """The --attest A/B legs: verified-samples/sec of the BATCHED device
    verifier vs the per-proof host path on an identical reconstructed
    proof queue, plus bytes-per-verified-sample of the deduped multiproof
    attestation vs fetching the same samples as independent share_proof
    responses.  Both paths must agree on every verdict (and every verdict
    must be True — the squares are honest), or the block reports the
    mismatch as a failure instead of a number."""
    from celestia_app_tpu.rpc.codec import share_proofs_from_attestation
    from celestia_app_tpu.serve.api import DasProvider, render
    from celestia_app_tpu.serve.verify import verify_proofs

    cache, roots = build_cache(args.heights, args.k, args.seed)
    provider = DasProvider(cache=cache)
    n = 2 * args.k
    s = args.attest
    rng = np.random.default_rng(args.seed + 7)
    axes = ("row", "col") if args.axes == "both" else (args.axes,)
    rounds = max(1, args.samples // s)

    proofs, proof_roots = [], []
    attest_bytes = independent_bytes = 0
    failures: list[str] = []
    for i in range(rounds):
        h = 1 + i % args.heights
        seen: set = set()
        while len(seen) < s:
            seen.add((
                int(rng.integers(0, n)), int(rng.integers(0, n)),
                axes[int(rng.integers(0, len(axes)))],
            ))
        spec = ",".join(f"{r}:{c}:{a}" for r, c, a in sorted(seen))
        payload = provider.attestation_payload(h, spec)
        attest_bytes += len(render(payload))
        for sample in payload["samples"]:
            independent_bytes += len(render(provider.share_proof_payload(
                h, sample["row"], sample["col"], sample["axis"]
            )))
        for proof in share_proofs_from_attestation(payload):
            proofs.append(proof)
            proof_roots.append(roots[h])

    total = len(proofs)
    walls: dict[str, float] = {}
    saved = os.environ.get("CELESTIA_VERIFY_MODE")
    try:
        for mode in ("batched", "host"):
            os.environ["CELESTIA_VERIFY_MODE"] = mode
            warm = min(64, total)
            verify_proofs(proofs[:warm], proof_roots[:warm])
            best = None
            verdicts = None
            for _ in range(3):
                t0 = time.perf_counter()
                verdicts = verify_proofs(proofs, proof_roots)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            walls[mode] = best
            if not all(verdicts):
                failures.append(
                    f"{mode} verify rejected "
                    f"{sum(1 for v in verdicts if not v)}/{total} honest "
                    "proofs"
                )
    finally:
        if saved is None:
            os.environ.pop("CELESTIA_VERIFY_MODE", None)
        else:
            os.environ["CELESTIA_VERIFY_MODE"] = saved

    return {
        "attest_samples": s,
        "rounds": rounds,
        "queue": total,
        "verified_per_s_batched": round(total / walls["batched"], 2),
        "verified_per_s_host": round(total / walls["host"], 2),
        "verify_speedup": round(walls["host"] / walls["batched"], 3),
        "attest_bytes_per_sample": round(attest_bytes / total, 2),
        "independent_bytes_per_sample": round(
            independent_bytes / total, 2
        ),
        "bytes_ratio": round(attest_bytes / independent_bytes, 4),
        "failures": failures,
    }


def run_url(args) -> dict:
    """Sample a live node's GET /das/share_proof over HTTP, verifying
    every --verify-th fetched proof client-side through the BATCHED
    verifier (serve/verify.py — the light-client contract, decided by
    the same program the serve side trusts).  A proof that fails to
    verify is a failure AND an SLO violation: the run reports `slo_burn`
    against --slo-ms with verify failures burning budget like drops.

    Every fetch carries the run's x-celestia-trace header, so the served
    node ADOPTS the loadgen's trace (trace/context.py) and its span rows
    stitch under one trace_id across both processes."""
    import urllib.request

    from celestia_app_tpu.rpc.codec import share_proof_from_json
    from celestia_app_tpu.serve.verify import verify_share_proof
    from celestia_app_tpu.trace.context import new_context, serialize_context

    wire = serialize_context(new_context(layer="loadgen", plane="url"))

    # Probe the square size from a first sample at (0, 0).
    def get(h, r, c):
        req = urllib.request.Request(
            f"{args.url}/das/share_proof?height={h}&row={r}&col={c}",
            headers={"x-celestia-trace": wire},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    first = get(args.height, 0, 0)
    n = 2 * first["square_size"]
    rng = np.random.default_rng(args.seed)
    lat_ms: list[float] = []
    failures: list[str] = []
    verified = 0
    verify_every = max(1, args.samples // max(args.verify, 1))
    t_start = time.perf_counter()
    for i in range(args.samples):
        r, c = int(rng.integers(0, n)), int(rng.integers(0, n))
        t0 = time.perf_counter()
        try:
            payload = get(args.height, r, c)
            if i % verify_every == 0:
                proof = share_proof_from_json(payload["proof"])
                root = bytes.fromhex(payload["data_root"])
                verified += 1
                if not verify_share_proof(proof, root):
                    failures.append(f"({r},{c}): proof failed verify")
                    continue
            lat_ms.append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001
            failures.append(f"({r},{c}): {type(e).__name__}: {e}")
    wall_s = time.perf_counter() - t_start
    lat_ms.sort()
    over = sum(1 for v in lat_ms if v > args.slo_ms) + len(failures)
    return {
        "metric": "das_loadgen",
        "mode": "url",
        "url": args.url,
        **_pass_stats(lat_ms, wall_s),
        "verified": verified,
        "slo_ms": args.slo_ms,
        "slo_burn": (
            round((over / args.samples) / 0.01, 3) if args.samples else 0.0
        ),
        "failures": failures[:5],
        "platform": None,
    }


def run_serve(args) -> int:
    """`--serve`: stand up one mini DAS node — deterministic synthetic
    squares admitted into a ForestCache behind a DasProvider, served on
    the standalone observability HTTP server (trace/exposition.py:
    /das/*, /metrics, /healthz, /das/coverage, /fleet) — and block until
    killed.  The first stdout line is a JSON ready record carrying the
    bound URL, so a parent process (the --urls fleet leg, tests) can
    spawn N of these with distinct $CELESTIA_NODE_ID and drive them as a
    local cluster."""
    from celestia_app_tpu.serve.api import DasProvider
    from celestia_app_tpu.trace.context import node_id
    from celestia_app_tpu.trace.exposition import (
        register_das_provider,
        serve_observability,
    )

    cache, _roots = build_cache(args.heights, args.k, args.seed)
    provider = DasProvider(cache=cache)
    register_das_provider(provider)
    # Warm the gather program off the clock so the first remote sample
    # does not pay the jit compile inside its measured latency.
    entry, _ = cache.get(1)
    provider.sampler.sample_batch(entry, [(0, 0)])
    srv = serve_observability("127.0.0.1", args.port)
    print(json.dumps({
        "serving": srv.url,
        "node_id": node_id(),
        "heights": args.heights,
        "k": args.k,
    }), flush=True)
    try:
        threading.Event().wait()  # parent kills the process when done
    except KeyboardInterrupt:
        pass
    srv.stop()
    return 0


def run_fleet(args) -> dict:
    """`--urls a,b,c`: replay ONE open-loop Poisson plan against every
    host of a multi-node cluster — each host receives the identical
    (arrival, row, col) schedule, so per-host proofs/sec are measured on
    identical work.  Cross-host latency quantiles come from the hosts'
    OWN /metrics: per-host celestia_proof_latency_seconds snapshots are
    scraped before and after the pass, deltaed, and bucket-merged
    (Histogram.merge — the same math GET /fleet serves), so the fleet
    numbers in DAS_rNN.json and the live /fleet endpoint can never
    drift apart.  Coverage at end of run is each host's
    /das/coverage?height= ratio (the sampled/verified bitmap the run
    itself ticked)."""
    import queue
    import urllib.request

    from celestia_app_tpu.trace.context import new_context, serialize_context
    from celestia_app_tpu.trace.fleet import parse_prometheus_text
    from celestia_app_tpu.trace.metrics import Histogram

    urls = [u.strip().rstrip("/") for u in args.urls.split(",") if u.strip()]
    if len(urls) < 2:
        raise SystemExit("--urls needs at least 2 comma-separated hosts")
    wire = serialize_context(new_context(layer="loadgen", plane="fleet"))

    def fetch(url: str, path: str) -> bytes:
        req = urllib.request.Request(
            url + path, headers={"x-celestia-trace": wire}
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.read()

    probe = json.loads(fetch(
        urls[0], f"/das/share_proof?height={args.height}&row=0&col=0"
    ))
    n = 2 * probe["square_size"]

    def proof_snapshot(url: str):
        _, _, hists = parse_prometheus_text(
            fetch(url, "/metrics").decode()
        )
        return hists.get("celestia_proof_latency_seconds")

    before = {u: proof_snapshot(u) for u in urls}

    # ONE deterministic plan, replayed per host: Poisson arrivals at
    # --rate (open-loop — latency includes queue delay), uniform DAS
    # coordinates over the full EDS.
    rng = np.random.default_rng(args.seed)
    plan = []
    t = 0.0
    for _ in range(args.samples):
        t += float(rng.exponential(1.0 / args.rate))
        plan.append((t, int(rng.integers(0, n)), int(rng.integers(0, n))))

    per_host: dict[str, list[float]] = {u: [] for u in urls}
    failures: list[str] = []
    walls: dict[str, float] = {}
    lock = threading.Lock()

    def drive(url: str):
        q: queue.Queue = queue.Queue()
        workers = max(1, min(args.threads, 8))
        t0 = time.perf_counter()

        def producer():
            for item in plan:
                delay = item[0] - (time.perf_counter() - t0)
                if delay > 0:
                    time.sleep(delay)
                q.put(item)
            for _ in range(workers):
                q.put(None)

        def worker():
            while True:
                got = q.get()
                if got is None:
                    return
                t_sched, r, c = got
                try:
                    fetch(
                        url,
                        f"/das/share_proof?height={args.height}"
                        f"&row={r}&col={c}",
                    )
                except Exception as e:  # noqa: BLE001 — a drop IS the measurement
                    with lock:
                        failures.append(
                            f"{url} ({r},{c}): {type(e).__name__}: {e}"
                        )
                    continue
                lat = (time.perf_counter() - t0) - t_sched
                with lock:
                    per_host[url].append(lat * 1e3)

        threads = [threading.Thread(target=producer, daemon=True)] + [
            threading.Thread(target=worker, daemon=True)
            for _ in range(workers)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        with lock:
            walls[url] = time.perf_counter() - t0

    drivers = [
        threading.Thread(target=drive, args=(u,), daemon=True) for u in urls
    ]
    for d in drivers:
        d.start()
    for d in drivers:
        d.join()

    # Server-side truth: delta each host's proof-latency histogram over
    # the pass, then bucket-merge — the cross-host quantile IS the merge
    # of the per-host snapshots (the /fleet invariant, pinned in
    # tests/test_fleet.py).
    deltas = []
    host_rows = []
    coverage_ratios = []
    for u in urls:
        after = proof_snapshot(u)
        delta = None
        if after is not None and before.get(u) is not None:
            delta = after.delta(before[u])
        elif after is not None:
            delta = after
        if delta is not None:
            deltas.append(delta)
        lats = sorted(per_host[u])
        try:
            cov = json.loads(fetch(
                u, f"/das/coverage?height={args.height}"
            ))["ratio"]
        except Exception:  # noqa: BLE001 — a host without the map still reports
            cov = None
        if cov is not None:
            coverage_ratios.append(cov)
        host_rows.append({
            "url": u,
            "samples": len(lats),
            "proofs_per_s": (
                round(len(lats) / walls[u], 2) if walls.get(u) else None
            ),
            "p50_ms": _percentile(lats, 0.50),
            "p99_ms": _percentile(lats, 0.99),
            "coverage_ratio": cov,
        })
    merged = Histogram.merge(deltas) if deltas else None

    def merged_ms(q):
        if merged is None or not merged.count():
            return None
        v = merged.quantile(q, phase="total")
        return round(v * 1e3, 3) if v is not None else None

    all_lats = sorted(v for lats in per_host.values() for v in lats)
    wall_s = max(walls.values()) if walls else 0.0
    import jax

    return {
        "metric": "das_loadgen",
        "mode": "fleet",
        "urls": urls,
        "requested": args.samples,
        "k": probe["square_size"],
        "samples": len(all_lats),
        "wall_s": round(wall_s, 3),
        "proofs_per_s": (
            round(len(all_lats) / wall_s, 2) if wall_s else None
        ),
        "proof_p50_ms": _percentile(all_lats, 0.50),
        "proof_p99_ms": _percentile(all_lats, 0.99),
        "fleet": {
            "hosts": host_rows,
            "cross_host_p50_ms": merged_ms(0.50),
            "cross_host_p99_ms": merged_ms(0.99),
            "coverage_ratio": (
                round(sum(coverage_ratios) / len(coverage_ratios), 6)
                if coverage_ratios else None
            ),
        },
        "failures": failures[:5],
        "platform": jax.default_backend(),
    }


def write_metrics_out(out_dir: str) -> None:
    """das_loadgen.prom + das_loadgen.jsonl: the serve-plane families off
    the live registry (the loadgen drove the REAL sampler metrics, so the
    artifact is exactly what a /metrics scrape would have seen)."""
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    os.makedirs(out_dir, exist_ok=True)
    keep = ("celestia_proof_", "celestia_serve_", "celestia_recoveries_",
            "celestia_chaos_")
    lines, emit = [], False
    for line in registry().render().splitlines():
        if line.startswith("# HELP") or line.startswith("# TYPE"):
            emit = line.split()[2].startswith(keep)
        if emit:
            lines.append(line)
    with open(os.path.join(out_dir, "das_loadgen.prom"), "w") as f:
        f.write("\n".join(lines) + "\n")
    rows = traced().export_jsonl("proof_serve")
    with open(os.path.join(out_dir, "das_loadgen.jsonl"), "w") as f:
        f.write(rows + "\n" if rows else "")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--heights", type=int, default=4)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--samples", type=int, default=2000)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--verify", type=int, default=64,
                    help="how many sampled proofs to verify against the root")
    ap.add_argument("--mode", choices=("batched", "host"), default=None,
                    help="pin $CELESTIA_SERVE_MODE for the run")
    ap.add_argument("--withhold-frac", type=float, default=0.0,
                    help="adversarial mix: a withholding proposer hides "
                         "this fraction of every height's shares "
                         "(exercises the 410 detection path under load)")
    ap.add_argument("--adv-seed", type=int, default=21,
                    help="seed for the adversary's withheld coordinate "
                         "sets (deterministic per height)")
    ap.add_argument("--heal", action="store_true",
                    help="with --withhold-frac: heal every detected "
                         "height (serve/heal.py) and re-run the plan, "
                         "reporting pre- vs post-heal proofs/sec and "
                         "time-to-first-healed-proof")
    ap.add_argument("--axes", choices=("row", "col", "both"), default="both",
                    help="sampling axis mix (light clients draw both)")
    ap.add_argument("--clients", type=int, default=0,
                    help="SWARM mode: simulate this many light clients "
                         "(10^4..10^6) with zipf tenant popularity and "
                         "open-loop Poisson arrivals")
    ap.add_argument("--tenants", type=int, default=8,
                    help="swarm: number of tenant namespaces per square")
    ap.add_argument("--zipf-a", type=float, default=1.2,
                    help="swarm: zipf exponent of client->tenant "
                         "popularity (bigger = more skew)")
    ap.add_argument("--rate", type=float, default=300.0,
                    help="swarm: offered load, samples/sec (open-loop "
                         "Poisson arrivals; latency includes queue delay)")
    ap.add_argument("--hot-frac", type=float, default=0.7,
                    help="swarm: fraction of arrivals on the newest "
                         "retained height")
    ap.add_argument("--historical-frac", type=float, default=0.02,
                    help="swarm: fraction hitting beyond-retention "
                         "heights (cache-busting rebuild path)")
    ap.add_argument("--historical", type=int, default=2,
                    help="swarm: how many beyond-retention heights exist")
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="swarm: per-tenant latency SLO target (99%% "
                         "objective; burn = violations / the 1%% budget)")
    ap.add_argument("--shard-sweep", default="1",
                    help="swarm: comma list of $CELESTIA_SERVE_SHARDS "
                         "settings to replay the identical plan under "
                         "(e.g. 1,8 — the scaling-curve sweep)")
    ap.add_argument("--spam-tenant", type=int, default=None,
                    help="qos: the spammer tenant id (default: the last "
                         "tenant — the least zipf-popular)")
    ap.add_argument("--proof-rate-limit", type=float, default=50.0,
                    help="qos: the spammer's per-tenant proof-rate limit "
                         "(proofs/sec; $CELESTIA_QOS <ns>.proof_rate)")
    ap.add_argument("--spam-mult", type=float, default=10.0,
                    help="qos: the spammer's offered rate as a multiple "
                         "of its limit")
    ap.add_argument("--qos-out", metavar="QOS_rNN.json",
                    help="run the QoS enforcement legs (baseline vs "
                         "spam under one $CELESTIA_QOS policy) and "
                         "write the bench_trend round record here")
    ap.add_argument("--attest", type=int, default=0, metavar="S",
                    help="run the attestation verify legs on top of the "
                         "closed-loop pass: S samples per GET "
                         "/das/attestation multiproof; records batched- "
                         "vs host-verified samples/sec and bytes-per-"
                         "verified-sample vs S independent share_proofs")
    ap.add_argument("--url", default=None,
                    help="sample a live node's /das/share_proof instead")
    ap.add_argument("--urls", default=None,
                    help="FLEET mode: comma list of >= 2 node URLs; the "
                         "identical open-loop plan replays against every "
                         "host, and the round record gains a `fleet` "
                         "block (per-host proofs/sec, cross-host p50/p99 "
                         "from bucket-merged /metrics histograms, "
                         "end-of-run /das/coverage ratio)")
    ap.add_argument("--serve", action="store_true",
                    help="stand up one mini DAS node (synthetic squares "
                         "behind the standalone observability server) "
                         "and block; first stdout line is the JSON "
                         "ready record with the bound URL")
    ap.add_argument("--port", type=int, default=0,
                    help="--serve: port to bind (default ephemeral)")
    ap.add_argument("--height", type=int, default=1,
                    help="height to sample in --url/--urls mode")
    ap.add_argument("--metrics-out", metavar="DIR")
    ap.add_argument("--round-out", metavar="DAS_rNN.json",
                    help="write the bench_trend round record here")
    args = ap.parse_args(argv)

    if args.clients:
        # The sweep needs that many host devices BEFORE jax first
        # initializes (all celestia jax imports are lazy; only numpy is
        # imported at module scope, so this is early enough).
        need = max(
            (int(s) for s in str(args.shard_sweep).split(",") if s.strip()),
            default=1,
        )
        flags = os.environ.get("XLA_FLAGS", "")
        if need > 1 and "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={need}"
            ).strip()

    if args.serve:
        return run_serve(args)

    saved = os.environ.get("CELESTIA_SERVE_MODE")
    if args.mode:
        os.environ["CELESTIA_SERVE_MODE"] = args.mode
    try:
        if args.qos_out:
            summary = run_qos(args)
        elif args.urls:
            summary = run_fleet(args)
        elif args.url:
            summary = run_url(args)
        elif args.clients:
            summary = run_swarm(args)
        else:
            summary = run_local(args)
            if args.attest:
                summary["verify"] = attest_verify_block(args)
                summary["failures"] = (
                    summary["failures"] + summary["verify"]["failures"]
                )
    finally:
        if args.mode:
            if saved is None:
                os.environ.pop("CELESTIA_SERVE_MODE", None)
            else:
                os.environ["CELESTIA_SERVE_MODE"] = saved

    print(json.dumps(summary), flush=True)
    if args.metrics_out:
        write_metrics_out(args.metrics_out)
    if args.qos_out:
        import re

        m = re.search(r"QOS_r(\d+)\.json$", os.path.basename(args.qos_out))
        record = {
            "n": int(m.group(1)) if m else 0,
            "schema": "qos-v1",
            "k": summary["k"],
            "clients": summary["clients"],
            "tenants": summary["tenants"],
            "rate": summary["rate"],
            "slo_ms": summary["slo_ms"],
            "spam_tenant": summary["spam_tenant"],
            "spam_namespace": summary["spam_namespace"],
            "proof_rate_limit": summary["proof_rate_limit"],
            "spam_mult": summary["spam_mult"],
            "spam_arrivals": summary["spam_arrivals"],
            "legs": summary["legs"],
            "platform": summary["platform"],
        }
        with open(args.qos_out, "w") as f:
            json.dump(record, f, indent=1)
        if summary["failures"]:
            for fail in summary["failures"]:
                print(f"FAIL: {fail}", file=sys.stderr)
            return 1
        spam_cols = summary["legs"]["spam"]["tenants"][
            summary["spam_tenant"]
        ]
        if not spam_cols["throttled"]:
            print("FAIL: the spammer was never throttled — the policy "
                  "enforced nothing", file=sys.stderr)
            return 1
        return 0
    if args.round_out:
        import re

        m = re.search(r"DAS_r(\d+)\.json$", os.path.basename(args.round_out))
        record = {
            "n": int(m.group(1)) if m else 0,
            "proofs_per_s": summary["proofs_per_s"],
            "proof_p50_ms": summary["proof_p50_ms"],
            "proof_p99_ms": summary["proof_p99_ms"],
            "samples": summary["samples"],
            "k": summary.get("k"),
            "mode": summary["mode"],
            "platform": summary.get("platform"),
        }
        if summary.get("verify") is not None:
            # The verify-plane A/B (--attest): batched vs host verified-
            # samples/sec + attestation vs independent bytes-per-sample
            # — the two series bench_trend rate-gates for this plane.
            record["verify"] = {
                k: v for k, v in summary["verify"].items()
                if k != "failures"
            }
        if summary.get("fleet") is not None:
            # The multi-node leg (--urls): per-host proofs/sec, the
            # bucket-merged cross-host tail, end-of-run coverage —
            # bench_trend's fleet series (same-platform rule; absence
            # from older rounds is a plan gap, not STALE).  The fleet
            # workload tag keeps the open-loop rate-capped headline from
            # gating against closed-loop saturation rounds.
            record["workload"] = "fleet"
            record["fleet"] = summary["fleet"]
        if summary.get("workload") == "swarm":
            # das-v2: the swarm round shape bench_trend learns — sweep
            # rows are the scaling curve, tenant columns the SLO story.
            record.update({
                "schema": "das-v2",
                "workload": "swarm",
                "clients": summary["clients"],
                "arrival": summary["arrival"],
                "rate": summary["rate"],
                "slo_ms": summary["slo_ms"],
                "headline_shards": summary["headline_shards"],
                "sweep": [
                    {
                        "shards": leg["shards"],
                        "proofs_per_s": leg["proofs_per_s"],
                        "proof_p50_ms": leg["proof_p50_ms"],
                        "proof_p99_ms": leg["proof_p99_ms"],
                        "samples": leg["samples"],
                    }
                    for leg in summary["sweep"]
                ],
                "tenants": summary["tenant_stats"],
            })
        with open(args.round_out, "w") as f:
            json.dump(record, f, indent=1)
    if summary.get("failures"):
        for fail in summary["failures"]:
            print(f"FAIL: {fail}", file=sys.stderr)
        return 1
    expected = args.samples - summary.get("withheld_hits", 0)
    if summary["samples"] < expected:
        print("FAIL: not every serveable sample was served", file=sys.stderr)
        return 1
    if summary.get("heal") is not None:
        post = summary["heal"]
        # With healing on, the post-heal pass must serve the FULL plan:
        # a previously-withheld coordinate that still 410s means the
        # heal did not restore service.
        if (post["post_heal"]["samples"] < args.samples
                or post["post_heal_withheld_hits"] > 0):
            print("FAIL: post-heal pass still hit withheld shares",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""DAS sampling load generator: thousands of queued share samples, p50/p99.

The txsim of the read side.  Where txsim floods BroadcastTx, this floods
the proof plane: N worker threads draw seeded-random (height, row, col)
coordinates over M cached squares and push them through the batched
ProofSampler queue (serve/sampler.py) — exactly the path the three RPC
planes serve — measuring per-sample wall latency and aggregate
proofs/sec.  A seeded subset of proofs is verified against the committed
DAH data root, so a loadgen run that "performs well" while serving
garbage fails loudly.

Runs crypto-free (no signing stack): squares are deterministic synthetic
blocks admitted straight into a ForestCache, so the tool measures the
serve plane, not block production.  `--mode host` drives the pure-host
fallback for an A/B number; `--url` instead samples a LIVE node's
GET /das/share_proof endpoint over HTTP.

  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/das_loadgen.py \
      --heights 4 --k 16 --samples 2000 --threads 8 \
      --metrics-out /tmp/das --round-out DAS_r01.json

Prints a one-line JSON summary; --metrics-out writes das_loadgen.prom
(the celestia_proof_* / celestia_serve_* families) + das_loadgen.jsonl;
--round-out writes the DAS_rNN.json record scripts/bench_trend.py reads
into its proofs/sec + proof-p99 trend series and regression gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402


def deterministic_square(k: int, seed: int):
    """One synthetic namespace-ordered ODS (the chaos_soak block shape)."""
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    ns = np.sort(rng.integers(0, 128, k * k).astype(np.uint8))
    ods = rng.integers(0, 256, (k * k, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    return ods.reshape(k, k, SHARE_SIZE)


def build_cache(heights: int, k: int, seed: int):
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.serve.cache import ForestCache

    cache = ForestCache(heights=heights, spill=heights)
    roots = {}
    for h in range(1, heights + 1):
        eds = ExtendedDataSquare.compute(deterministic_square(k, seed + h))
        cache.put(h, eds)
        roots[h] = eds.data_root()
    return cache, roots


def _run_plan(sampler, cache, plan, threads, verify_every, roots):
    """One threaded pass over the sampling plan; returns
    (lat_ms sorted, failures, withheld [(height, row, col)], wall_s).
    A ShareWithheld is NOT a failure — it is the adversarial 410 path
    the run exists to exercise — and it never kills a worker."""
    from celestia_app_tpu.serve.sampler import ShareWithheld

    latencies: list[float] = []
    failures: list[str] = []
    withheld: list[tuple[int, int, int]] = []
    lock = threading.Lock()
    cursor = iter(range(len(plan)))

    def worker():
        while True:
            with lock:
                i = next(cursor, None)
            if i is None:
                return
            h, r, c, axis = plan[i]
            entry, _ = cache.get(h)
            t0 = time.perf_counter()
            try:
                proof = sampler.share_proof(entry, r, c, axis=axis)
            except ShareWithheld:
                with lock:
                    withheld.append((h, r, c))
                continue
            except Exception as e:  # noqa: BLE001 — a drop IS the measurement
                with lock:
                    failures.append(f"({h},{r},{c}): {type(e).__name__}: {e}")
                return
            dt = time.perf_counter() - t0
            ok = True
            if i % verify_every == 0:
                ok = proof.verify(roots[h])
            with lock:
                latencies.append(dt)
                if not ok:
                    failures.append(f"({h},{r},{c}): proof failed verify")

    t_start = time.perf_counter()
    workers = [
        threading.Thread(target=worker, daemon=True) for _ in range(threads)
    ]
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    wall_s = time.perf_counter() - t_start
    return sorted(v * 1e3 for v in latencies), failures, withheld, wall_s


def _pass_stats(lat_ms, wall_s) -> dict:
    def pct(p):
        if not lat_ms:
            return None
        return round(lat_ms[min(len(lat_ms) - 1, int(p * len(lat_ms)))], 3)

    return {
        "samples": len(lat_ms),
        "wall_s": round(wall_s, 3),
        "proofs_per_s": round(len(lat_ms) / wall_s, 2) if wall_s else None,
        "proof_p50_ms": pct(0.50),
        "proof_p99_ms": pct(0.99),
    }


def run_local(args) -> dict:
    """Drive the in-process sampler queue with `threads` workers.

    With `--withhold-frac` the run becomes ADVERSARIAL: a withholding
    proposer (chaos/adversary.py, seeded by `--adv-seed`) hides that
    fraction of every height's shares, so workers exercise the 410
    detection path under load.  With `--heal` on top, every detected
    height is healed (serve/heal.py: gather survivors -> batched repair
    -> root-verify -> re-admit) and the SAME plan re-runs post-heal —
    the summary then reports pre-heal vs post-heal proofs/sec and the
    time from heal trigger to the first healed proof served."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.serve.sampler import ProofSampler

    adversarial = args.withhold_frac > 0
    if adversarial:
        chaos.install(
            f"seed={args.adv_seed},withhold_frac={args.withhold_frac}"
        )
    try:
        cache, roots = build_cache(args.heights, args.k, args.seed)
        sampler = ProofSampler()
        n = 2 * args.k
        rng = np.random.default_rng(args.seed)
        axes = (
            ("row", "col") if args.axes == "both" else (args.axes,)
        )
        plan = [
            (int(rng.integers(1, args.heights + 1)),
             int(rng.integers(0, n)), int(rng.integers(0, n)),
             axes[int(rng.integers(0, len(axes)))])
            for _ in range(args.samples)
        ]
        verify_every = max(1, args.samples // max(args.verify, 1))
        lat_ms, failures, withheld, wall_s = _run_plan(
            sampler, cache, plan, args.threads, verify_every, roots
        )

        heal_block = None
        if args.heal and withheld:
            from celestia_app_tpu.serve.api import DasProvider
            from celestia_app_tpu.serve.heal import HealingEngine

            provider = DasProvider(cache=cache, sampler=sampler)
            engine = HealingEngine(provider, name="loadgen")
            t_heal0 = time.perf_counter()
            hit_heights = sorted({h for h, _, _ in withheld})
            for h in hit_heights:
                engine.note("withheld", h)
            outcomes = dict(engine.process_pending())
            # Time to FIRST healed proof: the earliest previously-
            # withheld coordinate that now serves a verifying proof.
            first_healed_ms = None
            for h, r, c in withheld:
                if outcomes.get(h) != "healed":
                    continue
                proof = sampler.share_proof(provider.entry(h), r, c)
                if proof.verify(roots[h]):
                    first_healed_ms = round(
                        (time.perf_counter() - t_heal0) * 1e3, 3
                    )
                break
            post_lat, post_fail, post_withheld, post_wall = _run_plan(
                sampler, cache, plan, args.threads, verify_every, roots
            )
            failures.extend(post_fail)
            engine.close()
            heal_block = {
                "heights_healed": [
                    h for h in hit_heights if outcomes.get(h) == "healed"
                ],
                "outcomes": {str(h): o for h, o in outcomes.items()},
                "time_to_first_healed_proof_ms": first_healed_ms,
                "post_heal": _pass_stats(post_lat, post_wall),
                "post_heal_withheld_hits": len(post_withheld),
            }
    finally:
        if adversarial:
            chaos.uninstall()

    import jax

    summary = {
        "metric": "das_loadgen",
        "mode": os.environ.get("CELESTIA_SERVE_MODE", "") or "batched",
        "requested": args.samples,
        "heights": args.heights,
        "k": args.k,
        "threads": args.threads,
        "axes": args.axes,
        **_pass_stats(lat_ms, wall_s),
        "verified": (len(lat_ms) + verify_every - 1) // verify_every,
        "failures": failures[:5],
        "platform": jax.default_backend(),
        "cache": cache.stats(),
    }
    if adversarial:
        summary["withhold_frac"] = args.withhold_frac
        summary["adv_seed"] = args.adv_seed
        summary["withheld_hits"] = len(withheld)
    if heal_block is not None:
        summary["heal"] = heal_block
    return summary


def run_url(args) -> dict:
    """Sample a live node's GET /das/share_proof over HTTP."""
    import urllib.request

    # Probe the square size from a first sample at (0, 0).
    def get(h, r, c):
        with urllib.request.urlopen(
            f"{args.url}/das/share_proof?height={h}&row={r}&col={c}",
            timeout=30,
        ) as resp:
            return json.loads(resp.read())

    first = get(args.height, 0, 0)
    n = 2 * first["square_size"]
    rng = np.random.default_rng(args.seed)
    lat_ms: list[float] = []
    failures: list[str] = []
    t_start = time.perf_counter()
    for _ in range(args.samples):
        r, c = int(rng.integers(0, n)), int(rng.integers(0, n))
        t0 = time.perf_counter()
        try:
            get(args.height, r, c)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
        except Exception as e:  # noqa: BLE001
            failures.append(f"({r},{c}): {type(e).__name__}: {e}")
    wall_s = time.perf_counter() - t_start
    lat_ms.sort()

    def pct(p):
        if not lat_ms:
            return None
        return round(lat_ms[min(len(lat_ms) - 1, int(p * len(lat_ms)))], 3)

    return {
        "metric": "das_loadgen",
        "mode": "url",
        "url": args.url,
        "samples": len(lat_ms),
        "wall_s": round(wall_s, 3),
        "proofs_per_s": round(len(lat_ms) / wall_s, 2) if wall_s else None,
        "proof_p50_ms": pct(0.50),
        "proof_p99_ms": pct(0.99),
        "failures": failures[:5],
        "platform": None,
    }


def write_metrics_out(out_dir: str) -> None:
    """das_loadgen.prom + das_loadgen.jsonl: the serve-plane families off
    the live registry (the loadgen drove the REAL sampler metrics, so the
    artifact is exactly what a /metrics scrape would have seen)."""
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    os.makedirs(out_dir, exist_ok=True)
    keep = ("celestia_proof_", "celestia_serve_", "celestia_recoveries_",
            "celestia_chaos_")
    lines, emit = [], False
    for line in registry().render().splitlines():
        if line.startswith("# HELP") or line.startswith("# TYPE"):
            emit = line.split()[2].startswith(keep)
        if emit:
            lines.append(line)
    with open(os.path.join(out_dir, "das_loadgen.prom"), "w") as f:
        f.write("\n".join(lines) + "\n")
    rows = traced().export_jsonl("proof_serve")
    with open(os.path.join(out_dir, "das_loadgen.jsonl"), "w") as f:
        f.write(rows + "\n" if rows else "")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--heights", type=int, default=4)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--samples", type=int, default=2000)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--verify", type=int, default=64,
                    help="how many sampled proofs to verify against the root")
    ap.add_argument("--mode", choices=("batched", "host"), default=None,
                    help="pin $CELESTIA_SERVE_MODE for the run")
    ap.add_argument("--withhold-frac", type=float, default=0.0,
                    help="adversarial mix: a withholding proposer hides "
                         "this fraction of every height's shares "
                         "(exercises the 410 detection path under load)")
    ap.add_argument("--adv-seed", type=int, default=21,
                    help="seed for the adversary's withheld coordinate "
                         "sets (deterministic per height)")
    ap.add_argument("--heal", action="store_true",
                    help="with --withhold-frac: heal every detected "
                         "height (serve/heal.py) and re-run the plan, "
                         "reporting pre- vs post-heal proofs/sec and "
                         "time-to-first-healed-proof")
    ap.add_argument("--axes", choices=("row", "col", "both"), default="both",
                    help="sampling axis mix (light clients draw both)")
    ap.add_argument("--url", default=None,
                    help="sample a live node's /das/share_proof instead")
    ap.add_argument("--height", type=int, default=1,
                    help="height to sample in --url mode")
    ap.add_argument("--metrics-out", metavar="DIR")
    ap.add_argument("--round-out", metavar="DAS_rNN.json",
                    help="write the bench_trend round record here")
    args = ap.parse_args(argv)

    saved = os.environ.get("CELESTIA_SERVE_MODE")
    if args.mode:
        os.environ["CELESTIA_SERVE_MODE"] = args.mode
    try:
        summary = run_url(args) if args.url else run_local(args)
    finally:
        if args.mode:
            if saved is None:
                os.environ.pop("CELESTIA_SERVE_MODE", None)
            else:
                os.environ["CELESTIA_SERVE_MODE"] = saved

    print(json.dumps(summary), flush=True)
    if args.metrics_out:
        write_metrics_out(args.metrics_out)
    if args.round_out:
        import re

        m = re.search(r"DAS_r(\d+)\.json$", os.path.basename(args.round_out))
        record = {
            "n": int(m.group(1)) if m else 0,
            "proofs_per_s": summary["proofs_per_s"],
            "proof_p50_ms": summary["proof_p50_ms"],
            "proof_p99_ms": summary["proof_p99_ms"],
            "samples": summary["samples"],
            "k": summary.get("k"),
            "mode": summary["mode"],
            "platform": summary.get("platform"),
        }
        with open(args.round_out, "w") as f:
            json.dump(record, f, indent=1)
    if summary.get("failures"):
        for fail in summary["failures"]:
            print(f"FAIL: {fail}", file=sys.stderr)
        return 1
    expected = args.samples - summary.get("withheld_hits", 0)
    if summary["samples"] < expected:
        print("FAIL: not every serveable sample was served", file=sys.stderr)
        return 1
    if summary.get("heal") is not None:
        post = summary["heal"]
        # With healing on, the post-heal pass must serve the FULL plan:
        # a previously-withheld coordinate that still 410s means the
        # heal did not restore service.
        if (post["post_heal"]["samples"] < args.samples
                or post["post_heal_withheld_hits"] > 0):
            print("FAIL: post-heal pass still hit withheld shares",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

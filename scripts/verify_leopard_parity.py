"""Leopard RS parity closure tool.

The reference pins `rsmt2d.NewLeoRSCodec` (/root/reference/pkg/appconsts/
global_consts.go:92, dep go.mod:13) — klauspost/reedsolomon's leopard
additive-FFT codec. This repo implements the same construction as an exact
linear map (gf/leopard.py), but leopard's hardcoded Cantor-basis constants,
its index->basis bit order, and its GF(2^16) polynomial are not derivable
in this image (no Go toolchain, no leopard source on disk). This tool
closes the question the moment ANY externally produced evidence appears:

  1. leopard encode vectors — data shards in, parity shards out:
       {"kind": "encode_vectors", "field": 8 | 16,
        "data":   ["<hex shard>", ...],     # k shards, equal byte length
        "parity": ["<hex shard>", ...]}     # k parity shards from leopard
  2. a real celestia block's ODS + DAH:
       {"kind": "block",
        "shares":    ["<hex 512-byte share>", ...],   # row-major ODS, k*k
        "row_roots": ["<hex>", ...],                  # 2k NMT row roots
        "col_roots": ["<hex>", ...]}                  # 2k NMT column roots
     (hex values may also be given as base64 with a "b64:" prefix)

Run:
    PYTHONPATH=/root/repo python scripts/verify_leopard_parity.py EVIDENCE.json
    PYTHONPATH=/root/repo python scripts/verify_leopard_parity.py --selftest

Output: one JSON line reporting byte-parity under each of this repo's RS
constructions ("leopard", "vandermonde"). For encode vectors that match
NEITHER construction, a bounded search over the unverifiable degrees of
freedom runs automatically (Artin-Schreier root choice at each Cantor
chain step, grid index bit-reversal, data-half placement) and, on a hit,
prints the exact constants to pin in gf/leopard.py (FORCED_CANTOR_BASIS &
friends) — i.e. one discriminating vector both answers the parity question
and yields the fix.
"""

from __future__ import annotations

import base64
import binascii
import itertools
import json
import os
import sys
import tempfile

# The tool is evidence-checking, not a perf path: force CPU before jax
# loads so it never touches (or wedges) the accelerator tunnel. A
# sitecustomize may pre-register the accelerator platform, so pin the live
# jax config too — the env var alone does not take.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/celestia_jax_cache")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

CONSTRUCTIONS = ("leopard", "vandermonde")


def _unhex(s: str) -> bytes:
    if s.startswith("b64:"):
        return base64.b64decode(s[4:])
    return binascii.unhexlify(s)


# --------------------------------------------------------------------------
# Evidence kind 1: raw leopard encode vectors
# --------------------------------------------------------------------------


def _evidence_field(ev: dict, k: int) -> int:
    """The GF(2^m) the evidence was produced in. Defaults to leopard's own
    width rule (ff8 up to 256 shards, ff16 above) when the key is absent."""
    from celestia_app_tpu.gf.rs import field_for_width

    m = int(ev.get("field", field_for_width(2 * k).m))
    if m not in (8, 16):
        raise ValueError(f"field must be 8 or 16, got {m}")
    if 2 * k > (1 << m):
        raise ValueError(f"2k={2 * k} shards do not fit in GF(2^{m})")
    return m


def _leopard_encode(k: int, m: int, data: np.ndarray) -> np.ndarray:
    """Leopard-construction encode honouring an explicit field choice.

    RSCodec picks the field from the width alone (leopard's rule); external
    ff16 vectors can exist at any k, so this builds the generator for the
    requested field directly from the same leopard grid."""
    from celestia_app_tpu.gf.leopard import leopard_field, leopard_points

    f = leopard_field(m)
    pts = leopard_points(k, f)
    V = f.vandermonde(pts, k)
    G = f.matmul(V[k:], f.inv_matrix(V[:k]))
    sym = data if m == 8 else data.view("<u2")
    out = f.matmul(G, sym)
    return np.asarray(out, dtype=f.dtype).view(np.uint8) if m == 16 \
        else np.asarray(out, dtype=np.uint8)


def check_encode_vectors(ev: dict) -> dict:
    from celestia_app_tpu.gf.rs import RSCodec, field_for_width

    data = np.stack([np.frombuffer(_unhex(s), dtype=np.uint8) for s in ev["data"]])
    parity = np.stack([np.frombuffer(_unhex(s), dtype=np.uint8) for s in ev["parity"]])
    k = data.shape[0]
    if parity.shape != data.shape:
        raise ValueError(f"data {data.shape} vs parity {parity.shape} mismatch")
    if k & (k - 1):
        raise ValueError(f"k={k} is not a power of two")
    m = _evidence_field(ev, k)
    if m == 16 and data.shape[1] % 2:
        raise ValueError("ff16 shards must have even byte length")

    out = {"kind": "encode_vectors", "k": k, "share_bytes": int(data.shape[1]),
           "field": m, "results": {}}

    def _diff_row(got: np.ndarray) -> dict:
        match = bool(np.array_equal(got, parity))
        row = {"match": match}
        if not match:
            diff = np.argwhere(got != parity)
            row["first_mismatch"] = {
                "shard": int(diff[0][0]), "byte": int(diff[0][1]),
                "got": int(got[tuple(diff[0])]), "want": int(parity[tuple(diff[0])]),
            }
            row["mismatching_bytes"] = int(len(diff))
        return row

    out["results"]["leopard"] = _diff_row(_leopard_encode(k, m, data))
    # The vandermonde construction is only defined in this repo's own
    # width-derived field; in any other field it is definitionally a miss.
    if field_for_width(2 * k).m == m:
        out["results"]["vandermonde"] = _diff_row(
            RSCodec(k, "vandermonde").encode(data))
    else:
        out["results"]["vandermonde"] = {
            "match": False,
            "note": f"repo vandermonde at k={k} lives in "
                    f"GF(2^{field_for_width(2 * k).m}), evidence is GF(2^{m})"}

    if not out["results"]["leopard"]["match"]:
        out["basis_search"] = _search_leopard_constants(ev, data, parity, m)
    return out


def _candidate_bases(m: int, r: int):
    """Every DISTINCT length-r Cantor chain prefix b_0=1, b_{j+1} in
    {x, x+1} with x^2+x=b_j, in GF(2^m).

    Only the first r basis elements touch a 2k-point grid (r = ceil(log2
    2k)), so enumerating full length-m chains would re-test one effective
    prefix 2^(m-r) times; 2^(r-1) distinct prefixes is the whole space.
    """
    from celestia_app_tpu.gf.leopard import _solve_artin_schreier, leopard_field

    f = leopard_field(m)

    def chains(prefix: tuple[int, ...]):
        if len(prefix) == r:
            yield prefix
            return
        x = _solve_artin_schreier(f, prefix[-1])
        if x < 0:
            return
        for cand in (x, x ^ 1):
            if cand != 0:
                yield from chains(prefix + (cand,))

    return chains((1,))


def _extend_chain(m: int, prefix: tuple[int, ...]) -> tuple[int, ...]:
    """Deterministically continue a chain prefix to full length m (smallest
    root each step) — the grid never sees elements past the prefix, so any
    valid continuation serves for a FORCED_CANTOR_BASIS pin."""
    from celestia_app_tpu.gf.leopard import _solve_artin_schreier, leopard_field

    f = leopard_field(m)
    chain = list(prefix)
    while len(chain) < m:
        x = _solve_artin_schreier(f, chain[-1])
        if x <= 0:
            break  # chain cannot continue; a short pin still fixes the grid
        chain.append(x)
    return tuple(chain)


def _search_leopard_constants(
    ev: dict, data: np.ndarray, parity: np.ndarray, m: int
) -> dict:
    """Bounded sweep over the in-image-unverifiable leopard constants."""
    from celestia_app_tpu.gf.field import _field
    from celestia_app_tpu.gf.leopard import LEOPARD_POLY

    k = data.shape[0]
    f = _field(m, LEOPARD_POLY[m])
    sym = data if m == 8 else data.view("<u2")
    want = parity if m == 8 else parity.view("<u2")

    tried = 0
    budget = int(ev.get("search_budget", 4096))
    r = max(1, (2 * k - 1).bit_length())
    for basis in _candidate_bases(m, r):
        for bitrev, data_low in itertools.product((False, True), repeat=2):
            tried += 1
            if tried > budget:
                return {"hit": False, "tried": tried - 1, "exhausted": False,
                        "note": f"search budget {budget} reached; rerun with "
                                f"a larger \"search_budget\" in the evidence"}
            idx = np.arange(2 * k, dtype=np.uint32)
            if bitrev:
                rev = np.zeros_like(idx)
                for j in range(r):
                    rev |= ((idx >> j) & 1) << (r - 1 - j)
                idx = rev
            omega = np.zeros(2 * k, dtype=np.uint32)
            for j in range(r):
                omega ^= np.where((idx >> j) & 1, basis[j], 0).astype(np.uint32)
            pts = (np.concatenate([omega[:k], omega[k:]]) if data_low
                   else np.concatenate([omega[k:], omega[:k]])).astype(f.dtype)
            V = f.vandermonde(pts, k)
            try:
                G = f.matmul(V[k:], f.inv_matrix(V[:k]))
            except Exception:
                continue
            if np.array_equal(f.matmul(G, sym), want):
                full = _extend_chain(m, basis)
                return {"hit": True, "tried": tried,
                        "cantor_basis": [int(b) for b in basis],
                        "full_chain": [int(b) for b in full],
                        "index_bit_reversed": bitrev, "data_half": "low" if data_low else "high",
                        "pin": f"gf/leopard.py: FORCED_CANTOR_BASIS[{m}] = "
                               f"{tuple(int(b) for b in full)}  "
                               f"# first {r} elements evidence-determined"
                               + (" + flip index bit order" if bitrev else "")
                               + (" + data on LOW grid half" if data_low else "")}
    return {"hit": False, "tried": tried, "exhausted": True,
            "note": "no basis/bit-order/half assignment reproduces these "
                    "vectors - check the field polynomial or shard layout"}


# --------------------------------------------------------------------------
# Evidence kind 2: real block ODS + DAH roots
# --------------------------------------------------------------------------


def check_block(ev: dict) -> dict:
    from celestia_app_tpu.constants import SHARE_SIZE
    from celestia_app_tpu.da.eds import jit_pipeline

    shares = [_unhex(s) for s in ev["shares"]]
    n = len(shares)
    k = int(round(n ** 0.5))
    if k * k != n or k & (k - 1):
        raise ValueError(f"share count {n} is not a power-of-two square")
    for i, s in enumerate(shares):
        if len(s) != SHARE_SIZE:
            raise ValueError(f"share {i}: {len(s)} bytes, want {SHARE_SIZE}")
    want_rows = [_unhex(s) for s in ev["row_roots"]]
    want_cols = [_unhex(s) for s in ev["col_roots"]]
    if len(want_rows) != 2 * k or len(want_cols) != 2 * k:
        raise ValueError(f"want 2k={2 * k} row and col roots, "
                         f"got {len(want_rows)}/{len(want_cols)}")

    ods = np.frombuffer(b"".join(shares), dtype=np.uint8).reshape(k, k, SHARE_SIZE)
    out = {"kind": "block", "k": k, "results": {}}
    for construction in CONSTRUCTIONS:
        _, rr, cr, _ = jit_pipeline(k, construction)(ods)
        rows = [bytes(r.tobytes()) for r in np.asarray(rr)]
        cols = [bytes(c.tobytes()) for c in np.asarray(cr)]
        row = {"match": rows == want_rows and cols == want_cols}
        if not row["match"]:
            # ODS-derived roots (rows/cols 0..k-1 use only data + parity of
            # data rows) vs parity-quadrant roots localise the divergence.
            row["first_row_mismatch"] = next(
                (i for i, (a, b) in enumerate(zip(rows, want_rows)) if a != b), None)
            row["first_col_mismatch"] = next(
                (i for i, (a, b) in enumerate(zip(cols, want_cols)) if a != b), None)
        out["results"][construction] = row
    return out


# --------------------------------------------------------------------------
# Self-test: synthesize evidence from this repo's own codecs and make sure
# the checker discriminates constructions on it.
# --------------------------------------------------------------------------


def selftest() -> dict:
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
    from celestia_app_tpu.da.eds import jit_pipeline
    from celestia_app_tpu.gf.rs import RSCodec

    rng = np.random.default_rng(7)
    report = {}

    # 1) encode vectors produced by our leopard construction must come back
    #    leopard-match=True, vandermonde-match=False.
    k, width = 8, 64
    data = rng.integers(0, 256, (k, width), dtype=np.uint8)
    parity = RSCodec(k, "leopard").encode(data)
    ev = {"kind": "encode_vectors", "field": 8,
          "data": [d.tobytes().hex() for d in data],
          "parity": [p.tobytes().hex() for p in parity]}
    got = check_encode_vectors(ev)
    assert got["results"]["leopard"]["match"], got
    assert not got["results"]["vandermonde"]["match"], got
    report["encode_vectors"] = "ok"

    # 2) a foreign-but-valid basis must MISS both constructions and then be
    #    FOUND by the basis search. Flip the Artin-Schreier root choice at a
    #    chain step the 2k=16 grid actually uses (step 3), then re-derive
    #    the rest of the chain from the flipped element.
    from celestia_app_tpu.gf import leopard as leo
    chain = list(leo.cantor_basis(8))
    chain[3] ^= 1
    f8 = leo.leopard_field(8)
    for j in range(4, 8):
        chain[j] = leo._solve_artin_schreier(f8, chain[j - 1])
        assert chain[j] > 0, chain
    foreign = tuple(chain)
    orig_pin = leo.FORCED_CANTOR_BASIS[8]
    leo.FORCED_CANTOR_BASIS[8] = foreign
    leo.cantor_basis.cache_clear()
    try:
        parity2 = RSCodec(k, "leopard").encode(data)
    finally:
        leo.FORCED_CANTOR_BASIS[8] = orig_pin
        leo.cantor_basis.cache_clear()
    ev2 = dict(ev, parity=[p.tobytes().hex() for p in parity2])
    got2 = check_encode_vectors(ev2)
    assert not got2["results"]["leopard"]["match"], got2
    assert got2["basis_search"]["hit"], got2
    assert tuple(got2["basis_search"]["full_chain"]) == foreign, got2
    report["basis_search_recovers_foreign_basis"] = "ok"

    # 3) block evidence round-trip: roots from our own pipeline under
    #    leopard must match leopard and not vandermonde.
    k = 4
    ods = rng.integers(0, 256, (k, k, SHARE_SIZE), dtype=np.uint8)
    ns = np.sort(rng.integers(0, 64, k * k).astype(np.uint8)).reshape(k, k)
    ods[:, :, :NAMESPACE_SIZE] = 0
    ods[:, :, NAMESPACE_SIZE - 1] = ns
    _, rr, cr, _ = jit_pipeline(k, "leopard")(ods)
    ev3 = {"kind": "block",
           "shares": [ods[i, j].tobytes().hex() for i in range(k) for j in range(k)],
           "row_roots": [r.tobytes().hex() for r in np.asarray(rr)],
           "col_roots": [c.tobytes().hex() for c in np.asarray(cr)]}
    got3 = check_block(ev3)
    assert got3["results"]["leopard"]["match"], got3
    assert not got3["results"]["vandermonde"]["match"], got3
    report["block"] = "ok"

    # 4) the file round-trip the real invocation uses.
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(ev, f)
        path = f.name
    try:
        got4 = run_file(path)
        assert got4["results"]["leopard"]["match"], got4
    finally:
        os.unlink(path)
    report["file_roundtrip"] = "ok"
    return {"selftest": report, "verdict": "tool discriminates constructions; "
            "feed it real leopard vectors or a real block to close parity"}


def run_file(path: str) -> dict:
    with open(path) as f:
        ev = json.load(f)
    kind = ev.get("kind")
    if kind == "encode_vectors":
        return check_encode_vectors(ev)
    if kind == "block":
        return check_block(ev)
    raise ValueError(f"unknown evidence kind {kind!r} "
                     "(want \"encode_vectors\" or \"block\")")


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        return 2
    if sys.argv[1] == "--selftest":
        out = selftest()
    else:
        out = run_file(sys.argv[1])
        res = out["results"]
        out["verdict"] = (
            "PARITY CLOSED: leopard construction byte-identical"
            if res["leopard"]["match"] else
            "vandermonde construction matches (unexpected for reference data)"
            if res["vandermonde"]["match"] else
            "NO MATCH: see basis_search / first_mismatch for the fix trail")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())

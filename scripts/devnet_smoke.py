"""Devnet smoke: the full consensus story in one run.

Spawns a 4-validator gossip devnet (multi-process, real sockets), submits
a PayForBlobs through the tx client to a non-proposer, SIGKILLs a
validator and requires the chain to keep committing (the dead node's
proposer heights commit in round >= 1), then light-client-verifies a
fetched Commit record — +2/3 precommit signatures over a block id that
binds the data root, the previous app hash, AND the attested block time.

Run:  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/devnet_smoke.py
(Needs ~3-6 min on a warm compile cache; spawn_devnet pre-warms it.)
"""

import os, signal, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from celestia_app_tpu.rpc.devnet import spawn_devnet
from celestia_app_tpu.rpc.client import RemoteNode

env = dict(os.environ)
net = spawn_devnet(n=4, base_port=27410, block_interval_ms=150, mode="gossip", env=env, wait_s=240)
try:
    c = RemoteNode(net.urls[2], defer_status=True)
    c.wait_for_height(2, timeout_s=180)
    print("devnet live, height", c.status()["height"], flush=True)

    from celestia_app_tpu.crypto.keys import PrivateKey
    from celestia_app_tpu.user.tx_client import TxClient
    from celestia_app_tpu.shares import Blob
    key = PrivateKey.from_seed(b"account-0")
    client = TxClient(c, [key])
    from celestia_app_tpu.shares.namespace import Namespace
    ns = Namespace.v0(b"verifyns--")
    res = client.submit_pay_for_blob([Blob(ns, b"round-3 end-to-end blob")])
    print("PFB committed: code", res.code, "height", res.height, flush=True)
    assert res.code == 0

    h0 = c.status()["height"]
    net.procs[0].send_signal(signal.SIGKILL); net.procs[0].wait(timeout=10)
    # +6 so the checked window [h0+2, h] spans >= 4 heights — with 4
    # validators that guarantees at least one height whose round-0
    # proposer is the dead node.
    c.wait_for_height(h0 + 6, timeout_s=150)
    print("survived proposer kill:", c.status()["height"], ">=", h0 + 6, flush=True)

    from celestia_app_tpu.consensus import verify_commit, block_id
    h = c.status()["height"] - 1
    rec = c.commit(h)
    assert rec is not None, "no commit record"
    from celestia_app_tpu.crypto.keys import PrivateKey as PK
    vals = {}
    for i in range(4):
        k = PK.from_seed(f"validator-{i}".encode())
        vals[k.public_key().address()] = (k.public_key(), 100)
    ok = verify_commit(vals, c.chain_id, rec)
    print(f"commit@{h}: round={rec.round} time_ns={rec.time_ns} verify={ok}", flush=True)
    assert ok and rec.time_ns > 0
    assert rec.block_hash == block_id(rec.data_root, rec.prev_app_hash, rec.time_ns)
    dead = PK.from_seed(b"validator-0").public_key().address()
    # Start at h0+2: consensus for h0+1 was in flight when the SIGKILL
    # landed, so a precommit the dead node broadcast moments earlier can
    # legitimately appear in that height's record.
    rounds = set()
    dead_proposer_heights = []
    for hh in range(h0 + 2, h + 1):
        r = c.commit(hh)
        assert r is not None, f"node lost the commit record for {hh}"
        rounds.add(r.round)
        assert all(v.validator != dead for v in r.precommits), hh
        # THE property this drive exists to prove: a height whose
        # round-0 proposer is the dead validator must have committed in
        # a later round (rotation: sorted addrs shifted by height-1).
        order = sorted(vals)
        if order[(hh - 1) % len(order)] == dead:
            dead_proposer_heights.append(hh)
            assert r.round >= 1, (
                f"height {hh} had the dead round-0 proposer but "
                f"committed in round {r.round}"
            )
    print("post-kill commit rounds seen:", sorted(rounds),
          "dead-proposer heights:", dead_proposer_heights, flush=True)
    assert dead_proposer_heights, "window missed every dead-proposer height"
    print("VERIFY OK", flush=True)
finally:
    net.stop()

#!/usr/bin/env python
"""Render a flight-recorder bundle offline: what paged, what was burning,
and the journal rows around the trigger.

The flight recorder (celestia_app_tpu/trace/flight_recorder.py) writes
one JSON bundle per anomaly trigger under $CELESTIA_FLIGHT_DIR; this
script is the forensic reader — no live process, no imports from the
serving stack, just the bundle:

  python scripts/slo_report.py <bundle.json>        one bundle
  python scripts/slo_report.py <flight-dir>         the newest bundle
  python scripts/slo_report.py <flight-dir> --list  enumerate bundles
  ... --rows 10                                     journal rows shown
                                                    per table

Sections: the trigger and its context, the health/degradation state at
capture, the SLO table (state, fast/slow burn, objective — burning rows
first), the height-anatomy timeline block (which phase was critical for
the last heights when the page fired, and the latest height's phase /
gap budget), the per-tenant accounting snapshot, and the tail of the
most forensically relevant trace tables (slo_page, flight_dump,
block_journal, square_journal, chaos_injection, parity_mismatch,
wal_salvage) around the moment of capture.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

#: Tables rendered (in this order) when present in the bundle; anything
#: else in the capture is listed by row count only.
FOCUS_TABLES = (
    "slo_page",
    "chaos_injection",
    "parity_mismatch",
    "wal_salvage",
    "flight_dump",
    "block_journal",
    "square_journal",
)


def find_bundle(path: str) -> str:
    """Resolve a bundle path: a file is itself; a directory yields its
    newest flight-*.json."""
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        bundles = sorted(
            f for f in os.listdir(path)
            if f.startswith("flight-") and f.endswith(".json")
        )
        if not bundles:
            raise FileNotFoundError(f"no flight-*.json bundles under {path}")
        # Filenames embed capture unix-ns, so lexical max of the ts field
        # is the newest; sort on the embedded timestamp to be exact.
        bundles.sort(key=lambda f: f.split("-")[-2])
        return os.path.join(path, bundles[-1])
    raise FileNotFoundError(path)


def list_bundles(path: str) -> list[str]:
    if not os.path.isdir(path):
        raise NotADirectoryError(path)
    return sorted(
        f for f in os.listdir(path)
        if f.startswith("flight-") and f.endswith(".json")
    )


def _fmt_ns(ns: int | None) -> str:
    if not ns:
        return "-"
    import datetime

    dt = datetime.datetime.fromtimestamp(ns / 1e9, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%d %H:%M:%S.%f UTC")


def _slo_rows(slo_payload: dict) -> list[tuple[str, dict]]:
    """SLO rows, burning first (fast_burn, slow_burn, error, ok)."""
    order = {"fast_burn": 0, "slow_burn": 1, "error": 2, "ok": 3}
    slos = slo_payload.get("slos", {})
    return sorted(
        slos.items(),
        key=lambda kv: (order.get(kv[1].get("state"), 9), kv[0]),
    )


def render_timeline(block) -> list[str]:
    """The bundle's height-anatomy block (trace/timeline.py
    bundle_block): per-height critical phases, then the latest height's
    phase/gap budget — what the node was spending its height time on
    when the trigger fired.  Empty list when the bundle predates the
    timeline plane."""
    if not isinstance(block, dict):
        return []
    out = ["", "height anatomy (last "
           f"{len(block.get('records') or [])} of "
           f"{block.get('capacity', '-')} retained heights):"]
    records = block.get("records") or []
    if not records:
        out.append("  (no heights retained at capture)")
        return out
    out.append(f"  {'height':>8} {'critical phase':<16} "
               f"{'critical ms':>12} {'span ms':>10}  gaps")
    for rec in records:
        gaps = rec.get("gaps") or {}
        gap_s = ", ".join(
            f"{name}={ms}" for name, ms in sorted(gaps.items())
        ) or "-"
        out.append(
            f"  {rec.get('height', '?'):>8} "
            f"{rec.get('critical_phase') or '-':<16} "
            f"{rec.get('critical_ms', 0.0):>12} "
            f"{rec.get('span_ms', 0.0):>10}  {gap_s}"
        )
    latest = block.get("latest")
    if isinstance(latest, dict):
        out.append(f"  latest height {latest.get('height', '?')} "
                   "phase budget (ms):")
        phases = latest.get("phases") or {}
        for name, ms in sorted(phases.items(), key=lambda kv: -kv[1]):
            marker = (" <-- CRITICAL"
                      if name == latest.get("critical_phase") else "")
            out.append(f"    {name:<18} {ms:>10}{marker}")
    return out


def render(bundle: dict, rows_per_table: int = 8) -> str:
    out: list[str] = []
    trigger = bundle.get("trigger", "?")
    out.append(f"flight bundle: trigger={trigger!r} "
               f"captured={_fmt_ns(bundle.get('captured_unix_ns'))} "
               f"node={bundle.get('node_id', '-')} "
               f"pid={bundle.get('pid', '-')}")
    ctx = bundle.get("context") or {}
    if ctx:
        out.append("trigger context:")
        for k, v in sorted(ctx.items()):
            out.append(f"  {k} = {v}")
    health = bundle.get("healthz") or {}
    degraded = bundle.get("degraded")
    out.append(f"health: status={health.get('status', '-')}"
               + (f" degraded={degraded}" if degraded else ""))
    if bundle.get("chaos_spec"):
        out.append(f"chaos spec active: {bundle['chaos_spec']!r}")

    slo_payload = bundle.get("slo") or {}
    windows = slo_payload.get("windows", {})
    out.append("")
    out.append(f"SLOs (fast={windows.get('fast_s', '-')}s "
               f"slow={windows.get('slow_s', '-')}s, "
               f"evaluated={slo_payload.get('evaluated_unix_ms', '-')}):")
    slo_rows = _slo_rows(slo_payload)
    if not slo_rows:
        out.append("  (no evaluation retained in bundle)")
    else:
        out.append(f"  {'slo':<18} {'state':<10} {'burn fast':>10} "
                   f"{'burn slow':>10}  objective")
        for name, r in slo_rows:
            burn = r.get("burn", {})
            marker = " <-- PAGING" if r.get("state") == "fast_burn" else ""
            out.append(
                f"  {name:<18} {r.get('state', '?'):<10} "
                f"{burn.get('fast', '-'):>10} {burn.get('slow', '-'):>10}  "
                f"{r.get('objective', '')}{marker}"
            )

    out.extend(render_timeline(bundle.get("timeline")))

    ns_payload = bundle.get("namespaces") or {}
    tenants = ns_payload.get("namespaces") or {}
    if tenants:
        out.append("")
        out.append(f"tenants ({len(tenants)} namespace labels, "
                   f"top_n={ns_payload.get('top_n', '-')}):")
        by_shares = sorted(
            tenants.items(), key=lambda kv: -kv[1].get("shares", 0)
        )
        for lbl, t in by_shares[:10]:
            out.append(f"  {lbl:<20} blobs={t.get('blobs', 0):<8} "
                       f"shares={t.get('shares', 0):<10} "
                       f"bytes={t.get('bytes', 0)}")

    tables = bundle.get("tables") or {}
    out.append("")
    out.append(f"trace tables captured: "
               + (", ".join(f"{name}({len(rows)})"
                            for name, rows in sorted(tables.items()))
                  or "(none)"))
    for name in FOCUS_TABLES:
        rows = tables.get(name)
        if not rows:
            continue
        out.append("")
        out.append(f"{name} (last {min(rows_per_table, len(rows))} "
                   f"of {len(rows)} captured):")
        for row in rows[-rows_per_table:]:
            compact = {k: v for k, v in row.items() if v is not None}
            out.append("  " + json.dumps(compact, sort_keys=True)[:240])
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="bundle file or $CELESTIA_FLIGHT_DIR")
    ap.add_argument("--rows", type=int, default=8,
                    help="journal rows shown per table (default 8)")
    ap.add_argument("--list", action="store_true",
                    help="list bundles in the directory and exit")
    args = ap.parse_args(argv)

    try:
        if args.list:
            for name in list_bundles(args.path):
                print(name)
            return 0
        path = find_bundle(args.path)
    except (FileNotFoundError, NotADirectoryError) as e:
        print(f"slo_report: {e}", file=sys.stderr)
        return 2
    with open(path, encoding="utf-8") as f:
        bundle = json.load(f)
    print(f"# {path}")
    print(render(bundle, rows_per_table=max(1, args.rows)))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Chaos soak: N blocks under a seeded fault spec, bit-identical roots.

The claim under test is the paper's production premise: the device
pipeline sits on the consensus hot path, so injected faults may cost
LATENCY but never CORRECTNESS.  Four drills, one process:

  1. device soak     — N deterministic blocks streamed through the
                       BlockPipeline under dispatch/upload chaos; every
                       committed DAH root must be bit-identical to the
                       chaos-off run (retry, backoff, and even a
                       mid-soak degradation to staged/host are all
                       invisible in the roots).
  2. WAL tear drill  — votes journaled with `wal_torn_tail` injection;
                       a crash+restart replay must salvage every
                       complete record, refuse the conflicting re-sign,
                       and allow the idempotent one (double-sign safety
                       survives the torn tail).
  3. gossip drill    — a redundant flood over a lossy, duplicating,
                       transiently-failing link; the receiver-side
                       msg-id dedup must converge on exactly the unique
                       message set (drops healed by redundancy+retry,
                       dups absorbed).
  4. breaker drill   — a persistent injected device failure must flip
                       `pipeline_mode()` down the ladder to staged
                       within the breaker window, with
                       `celestia_degraded` and /healthz reporting the
                       degraded state.  Runs twice: from the default
                       fused seat AND from the leaf-hash-epilogue seat
                       ($CELESTIA_PIPE_FUSED=epi), which must walk the
                       extra fused_epi -> fused rung first — whichever
                       mode the autotuner seats, the ladder holds.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/chaos_soak.py \
      --blocks 20 --k 16 \
      --spec "seed=7,dispatch_fail=0.1,upload_stall_ms=20,gossip_drop=0.2,gossip_dup=0.1,wal_torn_tail=2"

Exits non-zero on any divergence; prints the per-seam
injection/recovery table either way.  tests/test_chaos.py runs a small
fixed-seed smoke through these same functions in tier-1.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

DEFAULT_SPEC = (
    "seed=7,dispatch_fail=0.1,upload_stall_ms=5,gossip_drop=0.2,"
    "gossip_dup=0.1,wal_torn_tail=2"
)


def _deterministic_blocks(n: int, k: int, seed: int = 1234):
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        shares = k * k
        ns = np.sort(rng.integers(0, 128, shares).astype(np.uint8))
        ods = rng.integers(0, 256, (shares, SHARE_SIZE), dtype=np.uint8)
        ods[:, :NAMESPACE_SIZE] = 0
        ods[:, NAMESPACE_SIZE - 1] = ns
        out.append((i, ods.reshape(k, k, SHARE_SIZE)))
    return out


def run_device_soak(n_blocks: int, k: int, spec: str) -> dict:
    """Stream n_blocks through the BlockPipeline chaos-off then chaos-on;
    returns {"roots_identical": bool, "final_mode": str, ...}."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.kernels.fused import pipeline_mode
    from celestia_app_tpu.parallel.pipeline import stream_blocks

    blocks = _deterministic_blocks(n_blocks, k)

    # An EMPTY programmatic install, not uninstall(): uninstall falls
    # back to $CELESTIA_CHAOS, and the whole point of this leg is a
    # baseline with no injection even when the env spec is set.
    chaos.install("")
    degrade.reset_for_tests()
    baseline = {
        tag: eds.data_root()
        for tag, eds in stream_blocks(iter(blocks), k, depth=2)
    }

    chaos.install(spec)
    try:
        chaotic = {
            tag: eds.data_root()
            for tag, eds in stream_blocks(iter(blocks), k, depth=2)
        }
        final_mode = pipeline_mode()
        degraded = degrade.degraded_state()
    finally:
        chaos.uninstall()
        degrade.reset_for_tests()
    mismatches = [
        t for t in baseline
        if chaotic.get(t) != baseline[t]
    ]
    return {
        "blocks": n_blocks,
        "k": k,
        "roots_identical": not mismatches and len(chaotic) == len(baseline),
        "mismatched_tags": mismatches,
        "final_mode": final_mode,
        "degraded": degraded,
    }


def run_wal_tear_drill(spec: str, wal_dir: str | None = None) -> dict:
    """Journal votes under torn-tail injection, crash, restart, and check
    double-sign safety + salvage."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.consensus.wal import VoteWAL

    PREVOTE, PRECOMMIT = 1, 2  # votes.py constants, sans its crypto import

    block_a, block_b = b"\xaa" * 32, b"\xbb" * 32
    tmp = wal_dir or tempfile.mkdtemp(prefix="chaos-wal-")
    path = os.path.join(tmp, "wal.jsonl")
    chaos.install(spec)
    try:
        wal = VoteWAL(path)
        signed = []
        for h in range(1, 9):
            for vt in (PREVOTE, PRECOMMIT):
                if wal.may_sign(h, 0, vt, block_a):
                    signed.append((h, 0, vt))
        # The spec's torn tails self-healed as appends continued (the
        # live truncate path).  For the restart-salvage leg the LAST
        # append must tear: re-arm one torn tail, sign, and crash
        # WITHOUT close — the durably fsync'd partial record is exactly
        # what the restart sees.
        chaos.install("seed=1,wal_torn_tail=1")
        assert wal.may_sign(99_000, 0, PREVOTE, block_a)
        signed.append((99_000, 0, PREVOTE))
        torn_on_disk = wal._torn
        del wal
    finally:
        chaos.uninstall()

    wal2 = VoteWAL(path)
    # Every completed record survives: the conflicting vote is refused at
    # every signed coordinate; the identical re-sign stays allowed (how a
    # restarted node rejoins and re-broadcasts without equivocating).
    refused = all(
        not wal2.may_sign(h, r, t, block_b) for h, r, t in signed
    )
    idempotent = all(wal2.may_sign(h, r, t, block_a) for h, r, t in signed)
    fresh = wal2.may_sign(99, 0, PREVOTE, block_b)  # new coords: free
    wal2.close()
    return {
        "signed": len(signed),
        "torn_on_disk": torn_on_disk,
        "salvaged_bytes": wal2.salvaged_bytes,
        "conflicts_refused": refused,
        "idempotent_resign_ok": idempotent,
        "fresh_coords_ok": fresh,
        "ok": refused and idempotent and fresh,
    }


class _FlakyPeer:
    """Fails every `fail_every`-th consensus() call (transient link)."""

    url = "chaos://flaky-peer"

    def __init__(self, fail_every: int = 5):
        self.fail_every = fail_every
        self.calls = 0
        self.delivered: list[dict] = []

    def consensus(self, msg: dict) -> dict:
        self.calls += 1
        if self.fail_every and self.calls % self.fail_every == 0:
            raise ConnectionError("chaos: transient peer failure")
        self.delivered.append(msg)
        return {"ok": True}


def run_gossip_drill(spec: str, n_msgs: int = 40, max_rounds: int = 12) -> dict:
    """Flood unique messages over a chaotic link (rpc/transport.deliver —
    the same path ConsensusDriver._send_to rides) until the receiver's
    dedup set converges on exactly the unique set, as the real mesh does:
    losses are healed by RE-FLOODING (relays, round timeouts, catch-up
    all re-offer messages), never by the sender knowing a drop happened.
    Must converge within `max_rounds` despite drops, dups, and a
    transiently failing peer — and dedup must keep the unique set exact
    despite the duplicate deliveries."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.rpc import transport

    peer = _FlakyPeer(fail_every=5)
    streak: dict = {}
    msgs = [
        {"kind": "vote", "height": 1, "vote": f"{i:04x}"}
        for i in range(n_msgs)
    ]
    expected = {transport.msg_id(m) for m in msgs}
    rounds = 0
    chaos.install(spec)
    try:
        while rounds < max_rounds:
            rounds += 1
            for msg in msgs:
                transport.deliver(
                    peer.consensus, msg, streak=streak, key=peer.url
                )
            # Reorder-delayed deliveries land on timer threads: wait them
            # out so the convergence check sees settled state.
            transport.drain_delayed()
            # Receiver-side flood termination: the dedup key handle() uses.
            if {transport.msg_id(m) for m in peer.delivered} == expected:
                break
    finally:
        chaos.uninstall()
    transport.drain_delayed()
    unique = {transport.msg_id(m) for m in peer.delivered}
    return {
        "sent_unique": n_msgs,
        "rounds": rounds,
        "deliveries": len(peer.delivered),
        "unique_delivered": len(unique),
        "converged": unique == expected,
        "ok": unique == expected and rounds <= max_rounds,
    }


def run_breaker_drill(k: int = 4, base_env: str | None = None) -> dict:
    """A persistent injected device failure must flip the ladder to
    staged within the breaker window, visible on /healthz.

    `base_env` pins $CELESTIA_PIPE_FUSED for the drill (e.g. "epi" to
    start from the leaf-hash-epilogue seat the autotuner may install —
    dispatch_fail targets the whole fused family, so that seat walks the
    extra fused_epi -> fused rung before landing on staged).  None keeps
    the ambient env.
    """
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.constants import SHARE_SIZE
    from celestia_app_tpu.kernels.fused import pipeline_mode
    from celestia_app_tpu.trace.exposition import health_payload

    saved_pipe = os.environ.get("CELESTIA_PIPE_FUSED")
    if base_env is not None:
        os.environ["CELESTIA_PIPE_FUSED"] = base_env
    chaos.install("")  # chaos-free even when $CELESTIA_CHAOS is set
    degrade.reset_for_tests()
    ods = np.zeros((k, k, SHARE_SIZE), dtype=np.uint8)
    healthy_root = ExtendedDataSquare.compute(ods).data_root()
    chaos.install("seed=11,dispatch_fail=1.0")
    try:
        degraded_root = ExtendedDataSquare.compute(ods).data_root()
        mode = pipeline_mode()
        health = health_payload()
    finally:
        chaos.uninstall()
        if base_env is not None:
            if saved_pipe is None:
                os.environ.pop("CELESTIA_PIPE_FUSED", None)
            else:
                os.environ["CELESTIA_PIPE_FUSED"] = saved_pipe
    result = {
        "mode_after": mode,
        "health_status": health.get("status"),
        "health_degraded": health.get("degraded"),
        "roots_identical": degraded_root == healthy_root,
        "ok": (
            mode == "staged"
            and health.get("status") == "DEGRADED"
            and health.get("degraded") == {"device": "staged"}
            and degraded_root == healthy_root
        ),
    }
    degrade.reset_for_tests()
    return result


def seam_table() -> str:
    """The per-seam injection/recovery counts, straight off the registry."""
    from celestia_app_tpu.trace.metrics import registry

    lines = [
        line for line in registry().render().splitlines()
        if line.startswith(("celestia_chaos_injections_total",
                            "celestia_recoveries_total"))
    ]
    return "\n".join(lines) or "(no injections fired)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=20)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--spec", default=DEFAULT_SPEC)
    args = ap.parse_args(argv)

    print(f"chaos_soak: spec={args.spec!r}", flush=True)
    failures = []

    dev = run_device_soak(args.blocks, args.k, args.spec)
    print(f"device soak: {dev['blocks']} blocks @ k={dev['k']} -> "
          f"roots_identical={dev['roots_identical']} "
          f"final_mode={dev['final_mode']} degraded={dev['degraded']}",
          flush=True)
    if not dev["roots_identical"]:
        failures.append(f"device soak diverged: {dev['mismatched_tags']}")

    wal = run_wal_tear_drill(args.spec)
    print(f"WAL tear drill: signed={wal['signed']} "
          f"torn_on_disk={wal['torn_on_disk']} "
          f"salvaged_bytes={wal['salvaged_bytes']} "
          f"conflicts_refused={wal['conflicts_refused']} "
          f"idempotent_resign_ok={wal['idempotent_resign_ok']}", flush=True)
    if not wal["ok"]:
        failures.append(f"WAL drill failed: {wal}")

    gos = run_gossip_drill(args.spec)
    print(f"gossip drill: {gos['sent_unique']} unique msgs converged in "
          f"{gos['rounds']} flood rounds -> {gos['deliveries']} deliveries, "
          f"{gos['unique_delivered']} unique after dedup "
          f"(converged={gos['converged']})", flush=True)
    if not gos["ok"]:
        failures.append(f"gossip drill failed: {gos}")

    brk_epi = run_breaker_drill(k=min(args.k, 8), base_env="epi")
    print(f"breaker drill (epi seat): mode_after={brk_epi['mode_after']} "
          f"health={brk_epi['health_status']} "
          f"roots_identical={brk_epi['roots_identical']}", flush=True)
    if not brk_epi["ok"]:
        failures.append(f"breaker drill (epi seat) failed: {brk_epi}")

    brk = run_breaker_drill(k=min(args.k, 8))
    print(f"breaker drill: mode_after={brk['mode_after']} "
          f"health={brk['health_status']} {brk['health_degraded']} "
          f"roots_identical={brk['roots_identical']}", flush=True)
    if not brk["ok"]:
        failures.append(f"breaker drill failed: {brk}")

    print("\nper-seam injection/recovery counts:")
    print(seam_table(), flush=True)

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("\nchaos_soak: OK — every drill held correctness under failure")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Chaos soak: N blocks under a seeded fault spec, bit-identical roots.

The claim under test is the paper's production premise: the device
pipeline sits on the consensus hot path, so injected faults may cost
LATENCY but never CORRECTNESS.  Four drills, one process:

  1. device soak     — N deterministic blocks streamed through the
                       BlockPipeline under dispatch/upload chaos; every
                       committed DAH root must be bit-identical to the
                       chaos-off run (retry, backoff, and even a
                       mid-soak degradation to staged/host are all
                       invisible in the roots).
  2. WAL tear drill  — votes journaled with `wal_torn_tail` injection;
                       a crash+restart replay must salvage every
                       complete record, refuse the conflicting re-sign,
                       and allow the idempotent one (double-sign safety
                       survives the torn tail).
  2b. sampling drill — DAS samples under injected proof.serve faults
                       (failed/slow batched proof dispatches): the
                       sampler must absorb every injection on the
                       pure-host fallback with proof bytes BIT-IDENTICAL
                       to the chaos-off batched run, all verifying
                       against the committed DAH data root.
  2c. speculation drill — speculative extends ($CELESTIA_PIPE_SPECULATE)
                       under injected dispatch faults and forced round
                       changes (the adopted square differs from the
                       speculated one): every mismatched claim must
                       DISCARD and recompute, with committed roots
                       bit-identical to the speculation-off run.
  2d. batched-fault drill — a persistent fault in the vmapped
                       multi-square dispatch ($CELESTIA_PIPE_BATCH) must
                       fall down the ladder (batched -> unbatched fused
                       -> staged), roots bit-identical throughout.
  2e. healing drill  — the detect -> repair -> re-serve loop
                       (serve/heal.py): a ShareWithheld / BadProofDetected
                       detection must TRIGGER batched repair, the
                       recovered square must root-verify against the
                       committed DAH before re-admission, the previously
                       withheld coordinate must serve a verifying proof,
                       mid-heal samples get the retryable 503-face, and
                       an irrecoverable height lands in quarantine.
  2g. shard-fault drill — the SHARDED serve plane's rung ladder
                       ($CELESTIA_SERVE_SHARDS, serve/shard.py): under
                       `shard_fail=1.0` every sharded gather degrades to
                       the single-device batched path, and compounded
                       with `proof_fail=1.0` on down to the host rung —
                       proof bytes bit-identical at every rung.
  2h. extend-shard drill — the SHARDED extend+DAH plane's rung ladder
                       ($CELESTIA_EXTEND_SHARDS, kernels/
                       panel_sharded.py): the committed-sharding
                       multi-chip pipeline must produce bit-identical
                       roots and a row-sharded EDS, and under
                       `extend_shard_fail=1.0` every collective dispatch
                       faults MID-schedule and the ladder walks
                       sharded_panel -> panel (the single-device
                       runner), roots unchanged.
  2f. quorum heal    — N serve-nodes with partial local share sets under
                       one withholding proposer: each detects through its
                       own sampling plane, repairs from the quorum's
                       UNION of surviving shares, and re-serves — with
                       per-node flight bundles proving who detected what
                       when (the ACeD oracle-committee story).
  3. gossip drill    — a redundant flood over a lossy, duplicating,
                       transiently-failing link; the receiver-side
                       msg-id dedup must converge on exactly the unique
                       message set (drops healed by redundancy+retry,
                       dups absorbed).
  4. breaker drill   — a persistent injected device failure must flip
                       `pipeline_mode()` down the ladder to staged
                       within the breaker window, with
                       `celestia_degraded` and /healthz reporting the
                       degraded state — AND the telemetry plane must
                       NOTICE on its own: the `degraded` SLO enters
                       fast-burn (a page) and the flight recorder writes
                       a bundle, all within the drill's block budget.
                       The drill reports DETECTION LATENCY — blocks and
                       wall-ms from the first injected failure to the
                       page — the ROADMAP's time-to-detection
                       measurement, now standing.  Runs twice: from the
                       default fused seat AND from the leaf-hash-
                       epilogue seat ($CELESTIA_PIPE_FUSED=epi), which
                       must walk the extra fused_epi -> fused rung first
                       — whichever mode the autotuner seats, the ladder
                       holds.

Every drill runs with the flight recorder armed ($CELESTIA_FLIGHT_DIR
defaults to a temp dir here); the summary prints a detection-latency
column per drill next to the per-seam injection/recovery counts.

Run:
  JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/chaos_soak.py \
      --blocks 20 --k 16 \
      --spec "seed=7,dispatch_fail=0.1,upload_stall_ms=20,gossip_drop=0.2,gossip_dup=0.1,wal_torn_tail=2"

Exits non-zero on any divergence; prints the per-seam
injection/recovery table either way.  tests/test_chaos.py runs a small
fixed-seed smoke through these same functions in tier-1.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The shard-fault drill (2g) exercises the SHARDED serve plane; on a
# host-only image that needs forced virtual devices, exactly like
# tests/conftest.py.  Harmless for every other drill (they ignore the
# extra devices), and an operator-set XLA_FLAGS is left alone.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402

DEFAULT_SPEC = (
    "seed=7,dispatch_fail=0.1,upload_stall_ms=5,gossip_drop=0.2,"
    "gossip_dup=0.1,wal_torn_tail=2"
)


def _arm_flight_recorder() -> str:
    """Ensure $CELESTIA_FLIGHT_DIR is set (temp dir when the operator
    didn't pick one) so every drill's anomalies produce bundles."""
    d = os.environ.get("CELESTIA_FLIGHT_DIR")
    if not d:
        d = tempfile.mkdtemp(prefix="chaos-flight-")
        os.environ["CELESTIA_FLIGHT_DIR"] = d
    return d


def _pin_flight_interval(seconds: float = 3600.0):
    """Pin the flight recorder's per-trigger rate limit to a
    drill-spanning window, returning a restore callable.

    The adversarial drills assert EXACTLY ONE bundle per trigger per
    drill; that must hold because the first detection black-boxed and
    the rest suppressed, not because the drill happened to finish inside
    the default 30 s window on a fast host (200-trial runs on the CPU
    fallback do not).  An operator-set interval is left alone."""
    key = "CELESTIA_FLIGHT_MIN_INTERVAL_S"
    prev = os.environ.get(key)
    if not prev:
        os.environ[key] = str(seconds)

    def restore() -> None:
        if not prev:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev

    return restore


def _first_dump_after(t0_ns: int, trigger: str | None = None) -> dict | None:
    """The first successful flight dump at/after `t0_ns` (optionally for
    one trigger) — how drills measure wall-clock time-to-detection.
    Reads the recorder's own ungated log, NOT the flight_dump trace row:
    with $CELESTIA_TRACE=off (the low-overhead measurement combo) the
    row vanishes but the bundle on disk is still the detection fact."""
    from celestia_app_tpu.trace.flight_recorder import recent_dumps

    dumps = recent_dumps(since_ns=t0_ns, trigger=trigger)
    return dumps[0] if dumps else None


def _detection(t0_ns: int, trigger: str | None = None,
               blocks: int | None = None) -> dict | None:
    """Detection-latency record for the summary table, or None when no
    dump landed after `t0_ns`."""
    row = _first_dump_after(t0_ns, trigger)
    if row is None:
        return None
    return {
        "by": row.get("trigger"),
        "blocks": blocks,
        "wall_ms": round((row["ts_ns"] - t0_ns) / 1e6, 3),
    }


def _deterministic_blocks(n: int, k: int, seed: int = 1234):
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        shares = k * k
        ns = np.sort(rng.integers(0, 128, shares).astype(np.uint8))
        ods = rng.integers(0, 256, (shares, SHARE_SIZE), dtype=np.uint8)
        ods[:, :NAMESPACE_SIZE] = 0
        ods[:, NAMESPACE_SIZE - 1] = ns
        out.append((i, ods.reshape(k, k, SHARE_SIZE)))
    return out


def run_device_soak(n_blocks: int, k: int, spec: str) -> dict:
    """Stream n_blocks through the BlockPipeline chaos-off then chaos-on;
    returns {"roots_identical": bool, "final_mode": str, ...}."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.kernels.fused import pipeline_mode
    from celestia_app_tpu.parallel.pipeline import stream_blocks

    blocks = _deterministic_blocks(n_blocks, k)

    # An EMPTY programmatic install, not uninstall(): uninstall falls
    # back to $CELESTIA_CHAOS, and the whole point of this leg is a
    # baseline with no injection even when the env spec is set.
    chaos.install("")
    degrade.reset_for_tests()
    baseline = {
        tag: eds.data_root()
        for tag, eds in stream_blocks(iter(blocks), k, depth=2)
    }

    chaos.install(spec)
    t0_ns = time.time_ns()
    try:
        chaotic = {
            tag: eds.data_root()
            for tag, eds in stream_blocks(iter(blocks), k, depth=2)
        }
        final_mode = pipeline_mode()
        degraded = degrade.degraded_state()
    finally:
        chaos.uninstall()
        degrade.reset_for_tests()
    mismatches = [
        t for t in baseline
        if chaotic.get(t) != baseline[t]
    ]
    return {
        "blocks": n_blocks,
        "k": k,
        "roots_identical": not mismatches and len(chaotic) == len(baseline),
        "mismatched_tags": mismatches,
        "final_mode": final_mode,
        "degraded": degraded,
        # Recovery usually absorbs p=0.1 faults without an anomaly; when
        # one DOES surface (a breaker trip mid-soak), this records how
        # long the plane took to notice.
        "detection": _detection(t0_ns),
    }


def run_wal_tear_drill(spec: str, wal_dir: str | None = None) -> dict:
    """Journal votes under torn-tail injection, crash, restart, and check
    double-sign safety + salvage."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.consensus.wal import VoteWAL

    PREVOTE, PRECOMMIT = 1, 2  # votes.py constants, sans its crypto import

    block_a, block_b = b"\xaa" * 32, b"\xbb" * 32
    tmp = wal_dir or tempfile.mkdtemp(prefix="chaos-wal-")
    path = os.path.join(tmp, "wal.jsonl")
    chaos.install(spec)
    t0_ns = time.time_ns()
    try:
        wal = VoteWAL(path)
        signed = []
        for h in range(1, 9):
            for vt in (PREVOTE, PRECOMMIT):
                if wal.may_sign(h, 0, vt, block_a):
                    signed.append((h, 0, vt))
        # The spec's torn tails self-healed as appends continued (the
        # live truncate path).  For the restart-salvage leg the LAST
        # append must tear: re-arm one torn tail, sign, and crash
        # WITHOUT close — the durably fsync'd partial record is exactly
        # what the restart sees.
        chaos.install("seed=1,wal_torn_tail=1")
        assert wal.may_sign(99_000, 0, PREVOTE, block_a)
        signed.append((99_000, 0, PREVOTE))
        torn_on_disk = wal._torn
        del wal
    finally:
        chaos.uninstall()

    wal2 = VoteWAL(path)
    # Every completed record survives: the conflicting vote is refused at
    # every signed coordinate; the identical re-sign stays allowed (how a
    # restarted node rejoins and re-broadcasts without equivocating).
    refused = all(
        not wal2.may_sign(h, r, t, block_b) for h, r, t in signed
    )
    idempotent = all(wal2.may_sign(h, r, t, block_a) for h, r, t in signed)
    fresh = wal2.may_sign(99, 0, PREVOTE, block_b)  # new coords: free
    wal2.close()
    return {
        "signed": len(signed),
        "torn_on_disk": torn_on_disk,
        "salvaged_bytes": wal2.salvaged_bytes,
        "conflicts_refused": refused,
        "idempotent_resign_ok": idempotent,
        "fresh_coords_ok": fresh,
        "ok": refused and idempotent and fresh,
        # The restart replay's salvage is the anomaly; the wal_salvage
        # flight dump is the plane noticing it.
        "detection": _detection(t0_ns, trigger="wal_salvage"),
    }


class _FlakyPeer:
    """Fails every `fail_every`-th consensus() call (transient link)."""

    url = "chaos://flaky-peer"

    def __init__(self, fail_every: int = 5):
        self.fail_every = fail_every
        self.calls = 0
        self.delivered: list[dict] = []

    def consensus(self, msg: dict) -> dict:
        self.calls += 1
        if self.fail_every and self.calls % self.fail_every == 0:
            raise ConnectionError("chaos: transient peer failure")
        self.delivered.append(msg)
        return {"ok": True}


def run_gossip_drill(spec: str, n_msgs: int = 40, max_rounds: int = 12) -> dict:
    """Flood unique messages over a chaotic link (rpc/transport.deliver —
    the same path ConsensusDriver._send_to rides) until the receiver's
    dedup set converges on exactly the unique set, as the real mesh does:
    losses are healed by RE-FLOODING (relays, round timeouts, catch-up
    all re-offer messages), never by the sender knowing a drop happened.
    Must converge within `max_rounds` despite drops, dups, and a
    transiently failing peer — and dedup must keep the unique set exact
    despite the duplicate deliveries."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.rpc import transport

    peer = _FlakyPeer(fail_every=5)
    streak: dict = {}
    msgs = [
        {"kind": "vote", "height": 1, "vote": f"{i:04x}"}
        for i in range(n_msgs)
    ]
    expected = {transport.msg_id(m) for m in msgs}
    rounds = 0
    chaos.install(spec)
    try:
        while rounds < max_rounds:
            rounds += 1
            for msg in msgs:
                transport.deliver(
                    peer.consensus, msg, streak=streak, key=peer.url
                )
            # Reorder-delayed deliveries land on timer threads: wait them
            # out so the convergence check sees settled state.
            transport.drain_delayed()
            # Receiver-side flood termination: the dedup key handle() uses.
            if {transport.msg_id(m) for m in peer.delivered} == expected:
                break
    finally:
        chaos.uninstall()
    transport.drain_delayed()
    unique = {transport.msg_id(m) for m in peer.delivered}
    return {
        "sent_unique": n_msgs,
        "rounds": rounds,
        "deliveries": len(peer.delivered),
        "unique_delivered": len(unique),
        "converged": unique == expected,
        "ok": unique == expected and rounds <= max_rounds,
    }


def run_breaker_drill(k: int = 4, base_env: str | None = None,
                      blocks: int = 8) -> dict:
    """A persistent injected device failure must flip the ladder to
    staged within the breaker window, visible on /healthz — and the
    telemetry plane must DETECT it end-to-end: sustained
    `dispatch_fail=1.0` has to drive the `degraded` SLO into fast-burn
    (a page) and produce a flight bundle within `blocks` blocks, with
    every committed root still bit-identical to the chaos-off run.
    Reports detection latency (blocks + wall-ms from first injection to
    the page).

    `base_env` pins $CELESTIA_PIPE_FUSED for the drill (e.g. "epi" to
    start from the leaf-hash-epilogue seat the autotuner may install —
    dispatch_fail targets the whole fused family, so that seat walks the
    extra fused_epi -> fused rung before landing on staged).  None keeps
    the ambient env.
    """
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.constants import SHARE_SIZE
    from celestia_app_tpu.kernels.fused import pipeline_mode
    from celestia_app_tpu.trace import flight_recorder, slo
    from celestia_app_tpu.trace.exposition import health_payload

    saved = {
        name: os.environ.get(name)
        for name in ("CELESTIA_PIPE_FUSED", "CELESTIA_SLO_TICK_S",
                     "CELESTIA_FLIGHT_DIR")
    }
    if base_env is not None:
        os.environ["CELESTIA_PIPE_FUSED"] = base_env
    _arm_flight_recorder()
    # Evaluate SLOs on every block-journal row: the drill measures
    # DETECTION latency, not tick-rate-limit latency.
    os.environ["CELESTIA_SLO_TICK_S"] = "0"
    chaos.install("")  # chaos-free even when $CELESTIA_CHAOS is set
    degrade.reset_for_tests()
    engine = slo._reset_for_tests()
    flight_recorder._reset_for_tests()  # drills must not inherit limits
    ods = np.zeros((k, k, SHARE_SIZE), dtype=np.uint8)
    healthy_root = ExtendedDataSquare.compute(ods).data_root()
    chaos.install("seed=11,dispatch_fail=1.0")
    t0_ns = time.time_ns()
    t0 = time.perf_counter()
    detect_blocks = None
    detect_wall_ms = None
    roots_identical = True
    blocks_run = 0
    try:
        for i in range(1, blocks + 1):
            blocks_run = i
            root = ExtendedDataSquare.compute(ods).data_root()
            roots_identical = roots_identical and (root == healthy_root)
            if engine.paged("degraded") and _first_dump_after(
                t0_ns, trigger="slo_fast_burn"
            ):
                detect_blocks = i
                detect_wall_ms = round((time.perf_counter() - t0) * 1e3, 3)
                break
        mode = pipeline_mode()
        health = health_payload()
    finally:
        chaos.uninstall()
        for name, val in saved.items():
            if val is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = val
    page_dump = _first_dump_after(t0_ns, trigger="slo_fast_burn")
    trip_dump = _first_dump_after(t0_ns, trigger="breaker_trip")
    result = {
        "mode_after": mode,
        "health_status": health.get("status"),
        "health_degraded": health.get("degraded"),
        "slo_health": health.get("slo"),
        "roots_identical": roots_identical,
        "paged": detect_blocks is not None,
        "detection_blocks": detect_blocks,
        "detection_wall_ms": detect_wall_ms,
        "flight_bundle": page_dump.get("path") if page_dump else None,
        "breaker_bundle": trip_dump.get("path") if trip_dump else None,
        "blocks_run": blocks_run,
        "detection": (
            {"by": "slo_fast_burn", "blocks": detect_blocks,
             "wall_ms": detect_wall_ms}
            if detect_blocks is not None else None
        ),
        "ok": (
            mode == "staged"
            and health.get("status") == "DEGRADED"
            and health.get("degraded") == {"device": "staged"}
            and roots_identical
            and detect_blocks is not None
            and page_dump is not None
            and trip_dump is not None
            and "degraded" in (health.get("slo") or {}).get("burning", [])
        ),
    }
    degrade.reset_for_tests()
    return result


def run_sampling_drill(k: int = 8, samples: int = 64,
                       spec: str = "seed=5,proof_fail=0.5,proof_slow_ms=2"
                       ) -> dict:
    """The serve plane's bit-exactness drill: under injected proof.serve
    faults (failed/slow batched dispatches), every DAS sample must still
    be answered — the sampler absorbs each injected failure by
    re-answering the batch on the pure-host path — and every proof must
    be BYTE-IDENTICAL to the chaos-off batched run and verify against
    the committed DAH data root.  The read-side mirror of the device
    soak's 'latency, never correctness' claim."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.serve.api import render
    from celestia_app_tpu.serve.cache import ForestCache
    from celestia_app_tpu.serve.sampler import ProofSampler
    from celestia_app_tpu.rpc.codec import to_jsonable
    from celestia_app_tpu.trace.metrics import registry

    _, ods = _deterministic_blocks(1, k, seed=515)[0]
    chaos.install("")  # baseline leg: no injection even with env chaos set
    eds = ExtendedDataSquare.compute(ods)
    root = eds.data_root()
    cache = ForestCache(heights=2, spill=2)
    entry = cache.put(1, eds)
    sampler = ProofSampler()
    rng = np.random.default_rng(99)
    n = 2 * k
    coords = [
        (int(rng.integers(0, n)), int(rng.integers(0, n)))
        for _ in range(samples)
    ]
    baseline = [
        render(to_jsonable(p)) for p in sampler.sample_batch(entry, coords)
    ]

    def _injections() -> float:
        for labels, val in registry().counter(
            "celestia_chaos_injections_total", ""
        ).samples():
            if labels.get("seam") == "proof.serve":
                return val
        return 0.0

    inj_before = _injections()
    chaos.install(spec)
    t0_ns = time.time_ns()
    try:
        # One batch per handful of coords so the fail probability gets
        # many dispatches to bite (one giant batch = one coin flip).
        chaotic = []
        for i in range(0, samples, 8):
            chaotic.extend(sampler.sample_batch(entry, coords[i:i + 8]))
    finally:
        chaos.uninstall()
    chaotic_bytes = [render(to_jsonable(p)) for p in chaotic]
    identical = chaotic_bytes == baseline
    verified = all(p.verify(root) for p in chaotic)
    injected = _injections() - inj_before
    return {
        "samples": samples,
        "k": k,
        "bit_identical": identical,
        "all_verify": verified,
        "injections": injected,
        "ok": identical and verified,
        "detection": _detection(t0_ns),
    }


def run_shard_fault_drill(k: int = 8, samples: int = 48,
                          shards: int = 8) -> dict:
    """The SHARDED serve plane's rung-ladder drill (serve/shard.py).

    Baseline: the same DAS plan answered by a sharded cache
    ($CELESTIA_SERVE_SHARDS) with no chaos.  Leg 1: `shard_fail=1.0`
    fails every sharded gather dispatch — the gather must degrade to the
    single-device batched path (celestia_recoveries_total
    {seam="proof.shard"}) with BIT-IDENTICAL proof bytes.  Leg 2:
    `shard_fail=1.0,proof_fail=1.0` compounds a batched-path fault on
    top — the sampler's host rung answers, still bit-identical.  The
    read-side ladder's full walk: sharded -> single-device -> host.
    """
    import jax

    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.rpc.codec import to_jsonable
    from celestia_app_tpu.serve.api import render
    from celestia_app_tpu.serve.cache import ForestCache
    from celestia_app_tpu.serve.sampler import ProofSampler
    from celestia_app_tpu.trace.metrics import registry

    shards = min(shards, len(jax.devices()))
    _, ods = _deterministic_blocks(1, k, seed=717)[0]
    saved = os.environ.get("CELESTIA_SERVE_SHARDS")
    os.environ["CELESTIA_SERVE_SHARDS"] = str(shards)

    def _recoveries(seam: str) -> float:
        for labels, val in registry().counter(
            "celestia_recoveries_total", ""
        ).samples():
            if labels.get("seam") == seam:
                return val
        return 0.0

    try:
        chaos.install("")  # baseline leg: no injection even with env chaos
        eds = ExtendedDataSquare.compute(ods)
        root = eds.data_root()
        cache = ForestCache(heights=2, spill=2)
        entry = cache.put(1, eds)
        sharded = bool(getattr(entry, "shards", 0))
        sampler = ProofSampler()
        rng = np.random.default_rng(727)
        n = 2 * k
        coords = [
            (int(rng.integers(0, n)), int(rng.integers(0, n)))
            for _ in range(samples)
        ]
        baseline = [
            render(to_jsonable(p))
            for p in sampler.sample_batch(entry, coords)
        ]
        legs = {}
        for name, spec_str, seam in (
            ("single_device", "seed=11,shard_fail=1.0", "proof.shard"),
            ("host", "seed=11,shard_fail=1.0,proof_fail=1.0",
             "proof.serve"),
        ):
            before = _recoveries(seam)
            chaos.install(spec_str)
            try:
                got = []
                for i in range(0, samples, 8):
                    got.extend(
                        sampler.sample_batch(entry, coords[i:i + 8])
                    )
            finally:
                chaos.install("")
            legs[name] = {
                "bit_identical": [
                    render(to_jsonable(p)) for p in got
                ] == baseline,
                "all_verify": all(p.verify(root) for p in got),
                "recoveries": _recoveries(seam) - before,
            }
        ok = sharded and all(
            leg["bit_identical"] and leg["all_verify"]
            and leg["recoveries"] > 0
            for leg in legs.values()
        )
        return {
            "samples": samples,
            "k": k,
            "shards": shards,
            "sharded": sharded,
            "legs": legs,
            "ok": ok,
        }
    finally:
        chaos.uninstall()
        if saved is None:
            os.environ.pop("CELESTIA_SERVE_SHARDS", None)
        else:
            os.environ["CELESTIA_SERVE_SHARDS"] = saved


def run_extend_shard_drill(k: int = 8, shards: int = 8,
                           panel_rows: int = 2) -> dict:
    """The SHARDED extend+DAH plane's rung-ladder drill
    (kernels/panel_sharded.py, $CELESTIA_EXTEND_SHARDS).

    Baseline: one square extended on the single-device materializing
    path (no chaos, no sharding), its DAH roots the reference.  Leg 1:
    the sharded-panel seam engaged with no chaos — the committed-
    sharding multi-chip pipeline must produce bit-identical roots AND a
    row-sharded EDS.  Leg 2: `extend_shard_fail=1.0` fails every
    sharded collective dispatch MID-schedule — guarded_dispatch must
    walk the ladder sharded_panel -> panel (the single-device runner),
    roots unchanged, ticking the dispatch-seam recoveries and leaving
    /healthz's degraded map on the panel rung.  The write-side ladder's
    top seam, drilled end-to-end.
    """
    import jax

    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.trace.metrics import registry

    # Clamp to the devices AND the square — then pow2-floor, exactly as
    # extend_shards() will (each device owns at least one ODS row; the
    # XOR butterfly needs a power of two): the drill's expectation must
    # match what the seam actually engages with, or a 6-device host
    # would fail the drill despite a healthy ladder.
    from celestia_app_tpu.kernels.panel_sharded import _pow2_floor

    shards = _pow2_floor(min(shards, len(jax.devices()), k))
    _, ods = _deterministic_blocks(1, k, seed=4242)[0]
    saved = {
        key: os.environ.get(key)
        for key in ("CELESTIA_EXTEND_SHARDS", "CELESTIA_PIPE_PANEL")
    }

    def _recoveries() -> float:
        total = 0.0
        for labels, val in registry().counter(
            "celestia_recoveries_total", ""
        ).samples():
            if labels.get("seam") == "device.dispatch":
                total += val
        return total

    try:
        chaos.install("")  # baseline leg: no injection even with env chaos
        degrade.reset_for_tests()
        os.environ.pop("CELESTIA_EXTEND_SHARDS", None)
        os.environ.pop("CELESTIA_PIPE_PANEL", None)
        root = ExtendedDataSquare.compute(ods).data_root()

        os.environ["CELESTIA_PIPE_PANEL"] = str(panel_rows)
        os.environ["CELESTIA_EXTEND_SHARDS"] = str(shards)
        from celestia_app_tpu.kernels.fused import pipeline_mode_for_k
        from celestia_app_tpu.kernels.panel_sharded import shards_for_k

        engaged = pipeline_mode_for_k(k) == "sharded_panel"
        eds_sharded = ExtendedDataSquare.compute(ods)
        sharded_identical = eds_sharded.data_root() == root
        n_shards = len(
            getattr(eds_sharded._eds, "addressable_shards", [])
        ) or 1

        before = _recoveries()
        t0_ns = time.time_ns()
        chaos.install("seed=13,extend_shard_fail=1.0")
        try:
            eds_faulted = ExtendedDataSquare.compute(ods)
        finally:
            chaos.install("")
        fault_identical = eds_faulted.data_root() == root
        state = degrade.degraded_state() or {}
        walked_to = state.get("device")
        recovered = _recoveries() - before
        ok = (
            engaged
            and shards_for_k(k) == shards
            and sharded_identical
            and n_shards == shards
            and fault_identical
            and walked_to == "panel"
            and recovered > 0
        )
        return {
            "k": k,
            "shards": shards,
            "engaged": engaged,
            "sharded_identical": sharded_identical,
            "eds_device_shards": n_shards,
            "fault_identical": fault_identical,
            "walked_to": walked_to,
            "recoveries": recovered,
            # Time-to-detection for the summary table: the breaker trip
            # black-boxes via the flight recorder when armed.
            "detection": _detection(t0_ns, trigger="breaker_trip"),
            "ok": ok,
        }
    finally:
        chaos.uninstall()
        degrade.reset_for_tests()
        for key, val in saved.items():
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val


def run_speculation_drill(k: int = 4, blocks: int = 6,
                          spec: str = "seed=3,dispatch_fail=0.3") -> dict:
    """The speculative-extend leg of the 'latency, never correctness'
    claim: with $CELESTIA_PIPE_SPECULATE=on, every block speculates the
    NEXT block's square ahead of adoption, and every other adoption is a
    ROUND CHANGE (the adopted square differs from the speculated one, so
    the claim must discard and recompute) — all under injected dispatch
    faults so a speculative dispatch also walks the retry/ladder path.
    Every committed root must be bit-identical to the speculation-off
    chaos-off run, and the discards must actually have fired."""
    import os

    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.da.eds import ExtendedDataSquare, speculator
    from celestia_app_tpu.trace.metrics import registry

    pairs = _deterministic_blocks(2 * blocks, k, seed=777)
    adopted = [ods for _tag, ods in pairs[:blocks]]
    reproposed = [ods for _tag, ods in pairs[blocks:]]

    chaos.install("")  # speculation-off, chaos-off baseline
    degrade.reset_for_tests()
    saved = os.environ.get("CELESTIA_PIPE_SPECULATE")
    os.environ.pop("CELESTIA_PIPE_SPECULATE", None)
    baseline = [ExtendedDataSquare.compute(o).data_root() for o in adopted]

    def _outcomes() -> dict:
        out = {"hit": 0.0, "discard": 0.0}
        for labels, val in registry().counter(
            "celestia_speculation_total", ""
        ).samples():
            out[labels.get("outcome", "?")] = val
        return out

    before = _outcomes()
    os.environ["CELESTIA_PIPE_SPECULATE"] = "on"
    chaos.install(spec)
    t0_ns = time.time_ns()
    try:
        roots = []
        sp = speculator()
        for i, ods in enumerate(adopted):
            if i % 2:
                # Round change: what was speculated is NOT what adoption
                # brings — the claim must discard and compute fresh.
                sp.speculate(reproposed[i], height=i, round_=0)
            else:
                sp.speculate(ods, height=i, round_=0)
            roots.append(ExtendedDataSquare.compute(ods).data_root())
    finally:
        chaos.uninstall()
        degrade.reset_for_tests()
        if saved is None:
            os.environ.pop("CELESTIA_PIPE_SPECULATE", None)
        else:
            os.environ["CELESTIA_PIPE_SPECULATE"] = saved
    after = _outcomes()
    hits = after["hit"] - before["hit"]
    discards = after["discard"] - before["discard"]
    identical = roots == baseline
    return {
        "blocks": blocks,
        "k": k,
        "roots_identical": identical,
        "hits": hits,
        "discards": discards,
        # Hits are best-effort under dispatch_fail (a failed speculative
        # dispatch simply never parks an entry); discards are the drill's
        # point and MUST have fired on every round change that resolved.
        "ok": identical and discards >= 1,
        "detection": _detection(t0_ns),
    }


#: DAS sample counts the withholding drill sweeps (the detection-
#: probability curve's x axis, after the Polar Coded Merkle Tree papers'
#: availability-attack benchmarks: P(detect | s samples) = 1 - (1-f)^s).
DAS_SAMPLE_COUNTS = (2, 4, 8, 16, 32, 64)


def _adv_square(k: int, seed: int = 515):
    """One committed square + its serve-plane state (cache entry,
    sampler, provider) — the fixture every adversary drill samples."""
    from celestia_app_tpu.da.dah import DataAvailabilityHeader
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.serve.api import DasProvider
    from celestia_app_tpu.serve.cache import ForestCache
    from celestia_app_tpu.serve.sampler import ProofSampler

    _, ods = _deterministic_blocks(1, k, seed=seed)[0]
    eds = ExtendedDataSquare.compute(ods)
    dah = DataAvailabilityHeader.from_eds(eds)
    cache = ForestCache(heights=2, spill=2)
    entry = cache.put(1, eds)
    provider = DasProvider(cache=cache, sampler=ProofSampler())
    return eds, dah, entry, provider


def run_withholding_drill(
    k: int = 8,
    fracs: tuple[float, ...] = (0.05, 0.10, 0.25),
    trials: int = 200,
    sample_counts: tuple[int, ...] = DAS_SAMPLE_COUNTS,
) -> dict:
    """The detection-probability-vs-sample-count measurement (the ROADMAP
    adversarial item, unblocked by PR 8's serve plane).

    A withholding proposer commits the honest DAH but hides a random
    `withhold_frac` of the EDS shares.  Light clients draw uniform DAS
    samples THROUGH ProofSampler — the same plane `GET /das/share_proof`
    serves — and a sample landing on a withheld share raises
    ShareWithheld: that failed sample IS detection.  For each fraction
    the drill runs `trials` independent clients, each drawing up to
    max(sample_counts) samples, and reports P(detect within s) for every
    s — NESTED sampling (s samples are the first s of the client's
    draw), so the measured curve is monotone in s by construction, as
    the analytic 1-(1-f)^s is.

    Then the repair-to-recovery leg: after detection, a full node
    gathers the surviving shares (everything the adversary did not
    withhold) and runs the BATCHED repair; recovery = repaired roots
    match the committed DAH.  The drill reports detect_ms (first
    detecting sample) + repair_ms separately.

    The honest leg pins the attack surface closed: a spec with every
    adversary key AT ZERO must serve proofs byte-identical to no chaos
    at all."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.repair import repair
    from celestia_app_tpu.rpc.codec import to_jsonable
    from celestia_app_tpu.serve.api import render
    from celestia_app_tpu.serve.sampler import ShareWithheld
    from celestia_app_tpu.trace import flight_recorder

    _arm_flight_recorder()
    eds, dah, entry, provider = _adv_square(k)
    honest_root = eds.data_root()
    n = 2 * k
    s_max = max(sample_counts)

    # Honest leg: adversary keys at 0 == no chaos, byte for byte.
    probe = [(r, c) for r in range(n) for c in range(min(n, 4))]
    chaos.install("")
    baseline = [
        render(to_jsonable(p))
        for p in provider.sampler.sample_batch(provider.entry(1), probe)
    ]
    chaos.install("seed=21,withhold_frac=0,malform_shares=0,wrong_root=0")
    keys_zero = [
        render(to_jsonable(p))
        for p in provider.sampler.sample_batch(provider.entry(1), probe)
    ]
    honest_identical = keys_zero == baseline

    flight_recorder._reset_for_tests()
    _restore_interval = _pin_flight_interval()
    try:
        t0_ns = time.time_ns()
        curves = []
        all_monotone = True
        for frac in fracs:
            chaos.install(f"seed=21,withhold_frac={frac}")
            ent = provider.entry(1)
            client = np.random.default_rng(4242)
            first_detect = []
            for _ in range(trials):
                idx = s_max  # not detected within the client's budget
                for i in range(s_max):
                    r = int(client.integers(0, n))
                    c = int(client.integers(0, n))
                    try:
                        proof = provider.sampler.share_proof(ent, r, c)
                    except ShareWithheld:
                        idx = i
                        break
                    # Served samples must still be honest, verifying proofs.
                    if not proof.verify(honest_root):
                        idx = -1  # invalid proof served: drill failure
                        break
                first_detect.append(idx)
            if any(i < 0 for i in first_detect):
                curves.append({"withhold_frac": frac, "p_detect": None,
                               "invalid_proof_served": True})
                all_monotone = False
                continue
            p_detect = {
                str(s): round(
                    sum(1 for i in first_detect if i < s) / trials, 4
                )
                for s in sample_counts
            }
            vals = [p_detect[str(s)] for s in sample_counts]
            monotone = all(b >= a for a, b in zip(vals, vals[1:]))
            all_monotone = all_monotone and monotone
            curves.append({
                "withhold_frac": frac,
                "p_detect": p_detect,
                "monotone": monotone,
                "expected_at_max": round(1 - (1 - frac) ** s_max, 4),
            })

        # Repair-to-recovery at the heaviest fraction: detect -> gather
        # survivors -> batched repair -> roots match the committed DAH.
        frac = max(fracs)
        chaos.install(f"seed=21,withhold_frac={frac}")
        adv = chaos.active_adversary()
        withheld = adv.withheld_set(1, n)
        ent = provider.entry(1)
        client = np.random.default_rng(777)
        t_detect0 = time.perf_counter()
        detect_ms = None
        for _ in range(64 * 64):
            r = int(client.integers(0, n))
            c = int(client.integers(0, n))
            try:
                provider.sampler.share_proof(ent, r, c)
            except ShareWithheld:
                detect_ms = (time.perf_counter() - t_detect0) * 1e3
                break
        recovered = False
        repair_ms = None
        if detect_ms is not None:
            present = np.ones((n, n), dtype=bool)
            for (r, c) in withheld:
                present[r, c] = False
            full = np.asarray(eds.squared())
            damaged = np.where(present[..., None], full, 0).astype(np.uint8)
            # Warm the sweep + pipeline compiles for this erasure shape (the
            # bench convention: a serving node's jit cache is warm; the
            # latency recorded is the repair, not the first-ever compile).
            try:
                repair(damaged.copy(), present, dah)
            except Exception:  # noqa: BLE001 — the timed leg reports it
                pass
            t_rep0 = time.perf_counter()
            try:
                out = repair(damaged, present, dah)
                repair_ms = (time.perf_counter() - t_rep0) * 1e3
                recovered = out.data_root() == honest_root
            except Exception as e:  # noqa: BLE001 — recorded as drill failure
                repair_ms = (time.perf_counter() - t_rep0) * 1e3
                recovered = False
                print(f"withholding drill: repair failed: {e}", file=sys.stderr)
        chaos.uninstall()
    finally:
        _restore_interval()
    wh_dumps = flight_recorder.recent_dumps(
        since_ns=t0_ns, trigger="withholding_detected"
    )
    return {
        "k": k,
        "trials": trials,
        "sample_counts": list(sample_counts),
        "detection": curves,
        "honest_identical": honest_identical,
        "all_monotone": all_monotone,
        "repair": {
            "withhold_frac": frac,
            "withheld_shares": len(withheld),
            "detect_ms": round(detect_ms, 3) if detect_ms else None,
            "repair_ms": round(repair_ms, 3) if repair_ms else None,
            "total_ms": (
                round(detect_ms + repair_ms, 3)
                if detect_ms and repair_ms else None
            ),
            "recovered": recovered,
        },
        # The rate limit makes a drill-long storm of detections ONE
        # bundle: the first detection black-boxes, the rest suppress.
        "flight_dumps": len(wh_dumps),
        "detection_signal": _detection(t0_ns, trigger="withholding_detected"),
        "ok": (
            honest_identical and all_monotone and recovered
            and len(wh_dumps) == 1
        ),
    }


def run_adversary_detection_drill(k: int = 8) -> dict:
    """Malformed-square + wrong-root injections must ALWAYS be detected
    (sampler verification or repair RootMismatch) and never served as
    valid proofs — with each adversary event producing exactly ONE
    flight bundle per drill under the rate limit.

      malform leg   every coordinate of the tampered square is sampled;
                    proofs over corrupted shares raise BadProofDetected,
                    everything served must verify against the honest
                    root; a corrupted SURVIVOR fed to repair raises
                    RootMismatch (the full-node face of the detection).
      wrong-root leg  the served root is forged: NO sample can produce
                    a proof chaining to it (all raise), and a repair
                    against a wrong commitment raises RootMismatch.
    """
    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.dah import DataAvailabilityHeader
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.da.repair import RootMismatch, repair
    from celestia_app_tpu.serve.sampler import BadProofDetected
    from celestia_app_tpu.trace import flight_recorder

    _arm_flight_recorder()
    eds, dah, entry, provider = _adv_square(k, seed=616)
    honest_root = eds.data_root()
    n = 2 * k
    full = np.asarray(eds.squared())
    flight_recorder._reset_for_tests()
    _restore_interval = _pin_flight_interval()
    try:
        t0_ns = time.time_ns()

        # --- malform leg -------------------------------------------------------
        chaos.install("seed=13,malform_shares=4")
        adv = chaos.active_adversary()
        mal_entry = provider.entry(1)
        corrupted = set(adv.malformed_coords(1, n))
        detected, served_valid, served_invalid = 0, 0, 0
        for r in range(n):
            for c in range(n):
                try:
                    proof = provider.sampler.share_proof(mal_entry, r, c)
                except BadProofDetected:
                    detected += 1
                    continue
                if proof.verify(honest_root):
                    served_valid += 1
                else:
                    served_invalid += 1
        malform_ok = (
            detected == len(corrupted)
            and served_invalid == 0
            and served_valid == n * n - len(corrupted)
        )

        # The full-node face: one corrupted SURVIVOR in a repair input must
        # reject the whole reconstruction (RootMismatch), never pass.
        present = np.ones((n, n), dtype=bool)
        present[k:, k:] = False
        damaged = np.where(present[..., None], full, 0).astype(np.uint8)
        damaged = adv.corrupt_square(1, damaged)
        try:
            repair(damaged, present, dah)
            repair_detected = False
        except RootMismatch:
            repair_detected = True

        # --- wrong-root leg ----------------------------------------------------
        chaos.install("seed=13,wrong_root=1")
        wr_entry = provider.entry(1)
        root_forged = wr_entry.data_root != honest_root
        wr_detected = 0
        probe = [(0, 0), (k, k), (n - 1, n - 1), (0, n - 1)]
        for r, c in probe:
            try:
                provider.sampler.share_proof(wr_entry, r, c)
            except BadProofDetected:
                wr_detected += 1
        # A light node repairing against a wrong commitment must refuse it.
        other = _deterministic_blocks(1, k, seed=617)[0][1]
        wrong_dah = DataAvailabilityHeader.from_eds(
            ExtendedDataSquare.compute(other)
        )
        clean = np.where(present[..., None], full, 0).astype(np.uint8)
        try:
            repair(clean, present, wrong_dah)
            wrong_root_repair_detected = False
        except RootMismatch:
            wrong_root_repair_detected = True
        chaos.uninstall()
    finally:
        _restore_interval()

    rm_dumps = flight_recorder.recent_dumps(
        since_ns=t0_ns, trigger="root_mismatch"
    )
    return {
        "k": k,
        "malform": {
            "corrupted_shares": len(corrupted),
            "detected": detected,
            "served_valid": served_valid,
            "served_invalid": served_invalid,
            "repair_detected": repair_detected,
            "ok": malform_ok and repair_detected,
        },
        "wrong_root": {
            "root_forged": root_forged,
            "samples_detected": wr_detected,
            "samples_probed": len(probe),
            "repair_detected": wrong_root_repair_detected,
            "ok": (
                root_forged
                and wr_detected == len(probe)
                and wrong_root_repair_detected
            ),
        },
        # One bundle per drill: every further root_mismatch suppresses
        # against the first under the default rate limit.
        "flight_dumps": len(rm_dumps),
        "detection": _detection(t0_ns, trigger="root_mismatch"),
        "ok": (
            malform_ok and repair_detected and root_forged
            and wr_detected == len(probe) and wrong_root_repair_detected
            and len(rm_dumps) == 1
        ),
    }


def _wait_until(predicate, timeout_s: float = 120.0,
                poll_s: float = 0.005) -> bool:
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout_s:
        if predicate():
            return True
        time.sleep(poll_s)
    return False


def run_healing_drill(k: int = 8, frac: float = 0.25,
                      quarantine_frac: float = 0.95) -> dict:
    """The detect -> repair -> re-serve loop, measured end to end through
    the real sampling plane (the ISSUE-12 tentpole; ACeD's oracle loop).

    Three legs on one node, healing on a live worker thread:

      withhold leg   a DAS client samples the adversarial serve view
                     until ShareWithheld fires; that detection TRIGGERS
                     the HealingEngine, samples arriving mid-heal get the
                     retryable HealingInProgress (the 503/UNAVAILABLE
                     face), and the drill measures detect-to-restored-
                     service: the previously-withheld coordinate must
                     serve a verifying proof from the healed height.
      wrong-root leg the tampered root is detected at the verification
                     gate, healed, and the recovered root must be
                     BIT-IDENTICAL to the committed DAH — with NOTHING
                     tampered served as valid at any point in the window.
      quarantine leg withholding beyond the k-survivor threshold: the
                     heal must land in quarantine (irrecoverable), stay
                     terminal (no heal storm, no retry of the impossible)
                     and black-box through `heal_quarantined`.

    Hard invariants (bench_trend gates these from the ADV round record):
    served_after_heal, root_identical, tampered_never_served, healed.
    The repair jit cache is warmed for the measured erasure shape first
    (the bench convention: a serving node's cache is warm; the number is
    the heal, not the first-ever compile)."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.repair import repair
    from celestia_app_tpu.serve import heal as heal_mod
    from celestia_app_tpu.serve.heal import HealingEngine, HealingInProgress
    from celestia_app_tpu.serve.sampler import BadProofDetected, ShareWithheld
    from celestia_app_tpu.trace import flight_recorder

    _arm_flight_recorder()
    chaos.install("")
    eds, dah, entry, provider = _adv_square(k, seed=909)
    honest_root = eds.data_root()
    # Second + third heights for the wrong-root and quarantine legs.
    extra = {}
    for h, seed in ((2, 910), (3, 911)):
        _, ods_h = _deterministic_blocks(1, k, seed=seed)[0]
        from celestia_app_tpu.da.eds import ExtendedDataSquare

        eds_h = ExtendedDataSquare.compute(ods_h)
        provider.cache.put(h, eds_h)
        extra[h] = eds_h.data_root()
    n = 2 * k
    engine = HealingEngine(provider, name="drill", retry_after_s=0.2).start()
    flight_recorder._reset_for_tests()
    # Rate limit OPEN (not drill-spanning): every terminal heal
    # transition must black-box — this drill asserts one bundle per
    # healed height plus the quarantine bundle.
    _restore_interval = _pin_flight_interval(0.0)
    tampered_served = False
    try:
        t0_ns = time.time_ns()
        # --- withhold leg --------------------------------------------------
        chaos.install(f"seed=41,withhold_frac={frac}")
        adv = chaos.active_adversary()
        withheld = sorted(adv.withheld_set(1, n))
        # Warm the repair compiles for this exact erasure shape so the
        # measured heal is the heal, not the first-ever jit build.
        view = provider.serve_view(1)
        honest = provider._honest_entry(1)
        w_shares, w_present = heal_mod.default_survivors(1, view, honest)
        try:
            repair(w_shares, w_present)
        except Exception:  # noqa: BLE001 — warmup only; the heal re-runs it
            pass
        client = np.random.default_rng(4321)
        detect_samples, hit = 0, None
        t_attack = time.perf_counter()
        while hit is None and detect_samples < n * n * 4:
            r, c = int(client.integers(0, n)), int(client.integers(0, n))
            detect_samples += 1
            try:
                ent = provider.entry(1)
                proof = provider.sampler.share_proof(ent, r, c)
                if not proof.verify(honest_root):
                    tampered_served = True
            except ShareWithheld:
                hit = (r, c)
        detect_ms = (time.perf_counter() - t_attack) * 1e3
        # Mid-heal: the worker is repairing right now — a sample must see
        # the RETRYABLE status, not a terminal detection.
        midheal_retryable = None
        try:
            provider.entry(1)
            midheal_retryable = False  # heal already done: can't observe
        except HealingInProgress:
            midheal_retryable = True
        healed = _wait_until(lambda: not engine.healing(1))
        restored = False
        if healed and hit is not None:
            ent = provider.entry(1)
            proof = provider.sampler.share_proof(ent, *hit)
            restored = proof.verify(honest_root)
        restored_ms = (time.perf_counter() - t_attack) * 1e3
        # Every previously-withheld coordinate serves now (spot cap 32).
        served_after_heal = restored
        ent = provider.entry(1)
        for r, c in withheld[:32]:
            p = provider.sampler.share_proof(ent, r, c)
            served_after_heal = served_after_heal and p.verify(honest_root)
        root_identical = (
            ent.data_root == honest_root
            and ent.eds.data_root() == honest_root
        )
        with engine._cv:
            single_rec = dict(engine._healed.get(1) or {})

        # --- wrong-root leg ------------------------------------------------
        chaos.install("seed=41,wrong_root=1")
        wr_detected = False
        try:
            ent2 = provider.entry(2)
            proof = provider.sampler.share_proof(ent2, 0, 0)
            if not proof.verify(extra[2]):
                tampered_served = True
        except BadProofDetected:
            wr_detected = True
        wr_healed = _wait_until(lambda: not engine.healing(2))
        ent2 = provider.entry(2)
        wr_root_identical = ent2.data_root == extra[2]
        wr_serves = provider.sampler.share_proof(ent2, 0, 0).verify(extra[2])

        # --- quarantine leg ------------------------------------------------
        chaos.install(f"seed=41,withhold_frac={quarantine_frac}")
        q_detected = False
        try:
            ent3 = provider.entry(3)
            provider.sampler.share_proof(ent3, 0, 0)
        except ShareWithheld:
            q_detected = True
        except BadProofDetected:
            pass
        _wait_until(lambda: not engine.healing(3))
        quarantined = engine.is_quarantined(3)
        # Terminal: the next detection answers 410 again (no heal storm).
        q_terminal = False
        try:
            ent3 = provider.entry(3)
            provider.sampler.share_proof(ent3, 0, 0)
        except ShareWithheld:
            q_terminal = True
        q_state = engine.state()["quarantined"].get("3") or {}
    finally:
        chaos.uninstall()
        _restore_interval()
        engine.close()
    completed = flight_recorder.recent_dumps(
        since_ns=t0_ns, trigger="heal_completed"
    )
    quarantined_dumps = flight_recorder.recent_dumps(
        since_ns=t0_ns, trigger="heal_quarantined"
    )
    return {
        "k": k,
        "withhold_frac": frac,
        "detect": {"samples": detect_samples, "ms": round(detect_ms, 3)},
        "midheal_retryable": midheal_retryable,
        "heal": single_rec,
        "restored_ms": round(restored_ms, 3),
        "served_after_heal": served_after_heal,
        "root_identical": root_identical,
        "tampered_never_served": not tampered_served,
        "wrong_root": {
            "detected": wr_detected,
            "healed": wr_healed,
            "root_identical": wr_root_identical,
            "serves": wr_serves,
        },
        "quarantine": {
            "frac": quarantine_frac,
            "detected": q_detected,
            "quarantined": quarantined,
            "terminal_after": q_terminal,
            "outcome": q_state.get("outcome"),
            "bundle": len(quarantined_dumps) >= 1,
        },
        "heal_bundles": len(completed),
        "detection": _detection(t0_ns, trigger="heal_completed"),
        "ok": (
            hit is not None
            and single_rec.get("outcome") == "healed"
            and served_after_heal
            and root_identical
            and not tampered_served
            and wr_detected and wr_healed and wr_root_identical and wr_serves
            and q_detected and quarantined and q_terminal
            and q_state.get("outcome") == "irrecoverable"
            and len(quarantined_dumps) >= 1
            and len(completed) >= 2
        ),
    }


def run_quorum_heal_drill(nodes: int = 3, k: int = 8,
                          frac: float = 0.25,
                          hold_p: float = 0.75) -> dict:
    """Scale the heal past one process: N honest serve-nodes, each
    retaining a PARTIAL local share set (every share held with
    probability `hold_p`, per-node seeded), under one withholding
    proposer.  Each node detects through its own sampling plane; each
    node's engine repairs from the UNION of the quorum's surviving
    shares (what peers can answer, minus what the adversary withholds,
    every gathered share leaf-digest-verified against the committed
    forest) and re-serves.  Per-node flight bundles (heal_completed
    carries node/height/phase latencies; the rate limit is opened so
    every node's detection black-boxes) prove who detected what when.

    Invariants: every node serves the previously-withheld coordinate
    with a proof verifying the committed root post-heal, and every
    node's recovered root is bit-identical to the committed DAH."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.da.repair import repair
    from celestia_app_tpu.serve import heal as heal_mod
    from celestia_app_tpu.serve.api import DasProvider
    from celestia_app_tpu.serve.cache import ForestCache
    from celestia_app_tpu.serve.heal import HealingEngine
    from celestia_app_tpu.serve.sampler import ProofSampler, ShareWithheld
    from celestia_app_tpu.trace import flight_recorder

    _arm_flight_recorder()
    chaos.install("")
    _, ods = _deterministic_blocks(1, k, seed=515)[0]
    n = 2 * k
    # Per-node partial retention + the quorum union every healer gathers
    # from.  Seeded so the drill (and its ADV round record) reproduces.
    mask_rng = np.random.default_rng(2718)
    local = [mask_rng.random((n, n)) < hold_p for _ in range(nodes)]
    union = np.logical_or.reduce(local)

    def union_gather(height, view, honest):
        shares, present = heal_mod.default_survivors(height, view, honest)
        return shares, present & union

    providers, engines, roots = [], [], []
    for i in range(nodes):
        eds_i = ExtendedDataSquare.compute(ods)  # own handle per node
        cache_i = ForestCache(heights=2, spill=2)
        cache_i.put(1, eds_i)
        provider_i = DasProvider(cache=cache_i, sampler=ProofSampler())
        providers.append(provider_i)
        roots.append(eds_i.data_root())
        engines.append(HealingEngine(
            provider_i, name=f"node{i}", survivors=union_gather,
            retry_after_s=0.2,
        ))
    honest_root = roots[0]
    flight_recorder._reset_for_tests()
    _restore_interval = _pin_flight_interval(0.0)  # one bundle per NODE
    try:
        t0_ns = time.time_ns()
        chaos.install(f"seed=51,withhold_frac={frac}")
        adv = chaos.active_adversary()
        withheld = sorted(adv.withheld_set(1, n))
        # Warm the union erasure shape once (shared jit cache).
        view = providers[0].serve_view(1)
        honest = providers[0]._honest_entry(1)
        w_shares, w_present = union_gather(1, view, honest)
        try:
            repair(w_shares, w_present)
        except Exception:  # noqa: BLE001 — warmup only
            pass
        t_attack = time.perf_counter()
        detections_per_node = []
        for i, provider_i in enumerate(providers):
            client = np.random.default_rng(7000 + i)
            hit, samples = None, 0
            t_n0 = time.perf_counter()
            while hit is None and samples < n * n * 4:
                r, c = int(client.integers(0, n)), int(client.integers(0, n))
                samples += 1
                try:
                    ent = provider_i.entry(1)
                    provider_i.sampler.share_proof(ent, r, c)
                except ShareWithheld:
                    hit = (r, c)
            detections_per_node.append({
                "node": f"node{i}",
                "samples": samples,
                "ms": round((time.perf_counter() - t_n0) * 1e3, 3),
                "coord": list(hit) if hit else None,
            })
        # Collective recovery: every detecting node heals from the union.
        heal_records = []
        for i, engine in enumerate(engines):
            engine.process_pending()
            with engine._cv:
                heal_records.append(dict(engine._healed.get(1) or {}))
        # Restored service: the first detector's previously-withheld
        # coordinate serves on EVERY node, proofs verifying the
        # committed root.
        first_hit = tuple(detections_per_node[0]["coord"])
        served, roots_ok = True, True
        for provider_i in providers:
            ent = provider_i.entry(1)
            p = provider_i.sampler.share_proof(ent, *first_hit)
            served = served and p.verify(honest_root)
            roots_ok = roots_ok and (
                ent.data_root == honest_root
                and ent.eds.data_root() == honest_root
            )
        total_ms = (time.perf_counter() - t_attack) * 1e3
    finally:
        chaos.uninstall()
        _restore_interval()
        for engine in engines:
            engine.close()
    completed = flight_recorder.recent_dumps(
        since_ns=t0_ns, trigger="heal_completed"
    )
    healed_nodes = sum(
        1 for rec in heal_records if rec.get("outcome") == "healed"
    )
    return {
        "nodes": nodes,
        "k": k,
        "withhold_frac": frac,
        "hold_p": hold_p,
        "withheld_shares": len(withheld),
        "union_coverage": round(float(union.mean()), 4),
        "detections": detections_per_node,
        "heals": heal_records,
        "healed_nodes": healed_nodes,
        "served_after_heal": served,
        "root_identical": roots_ok,
        "total_ms": round(total_ms, 3),
        "heal_bundles": len(completed),
        "detection": _detection(t0_ns, trigger="heal_completed"),
        "ok": (
            healed_nodes == nodes
            and served and roots_ok
            and all(d["coord"] for d in detections_per_node)
            and len(completed) == nodes
        ),
    }


def run_batched_fault_drill(k: int = 4, blocks: int = 6,
                            batch: int = 2) -> dict:
    """A persistent batched-dispatch fault must fall DOWN the ladder, not
    lose blocks: dispatch_fail=1.0 (the fused family, batched program
    included) forces every coalesced dispatch onto the per-square
    fallback (celestia_recoveries_total{outcome=unbatched}), whose own
    failures then walk fused -> staged via the breaker — with every root
    bit-identical to the chaos-off unbatched run."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos import degrade
    from celestia_app_tpu.kernels.fused import pipeline_mode
    from celestia_app_tpu.parallel.pipeline import stream_blocks
    from celestia_app_tpu.trace.metrics import registry

    pairs = _deterministic_blocks(blocks, k, seed=313)

    chaos.install("")
    degrade.reset_for_tests()
    baseline = {
        tag: eds.data_root()
        for tag, eds in stream_blocks(iter(pairs), k, depth=2, batch=1)
    }

    def _unbatched_falls() -> float:
        for labels, val in registry().counter(
            "celestia_recoveries_total", ""
        ).samples():
            if (labels.get("seam") == "device.dispatch"
                    and labels.get("outcome") == "unbatched"):
                return val
        return 0.0

    before = _unbatched_falls()
    chaos.install("seed=17,dispatch_fail=1.0")
    t0_ns = time.time_ns()
    try:
        chaotic = {
            tag: eds.data_root()
            for tag, eds in stream_blocks(
                iter(pairs), k, depth=max(2, batch), batch=batch
            )
        }
        final_mode = pipeline_mode()
    finally:
        chaos.uninstall()
        degrade.reset_for_tests()
    falls = _unbatched_falls() - before
    identical = chaotic == baseline
    return {
        "blocks": blocks,
        "k": k,
        "batch": batch,
        "roots_identical": identical,
        "unbatched_falls": falls,
        "final_mode": final_mode,
        # The fused family is fully failed, so the ladder must have
        # landed on staged AND the batched rung must have stepped down
        # through the unbatched fallback at least once on the way.
        "ok": identical and falls >= 1 and final_mode == "staged",
        "detection": _detection(t0_ns),
    }


def run_attestation_drill(k: int = 4, samples: int = 12) -> dict:
    """The verify plane's fault drill, attestation-shaped.

    Leg 1 (verify_fail identity): one deduped multiproof attestation is
    assembled, its proofs reconstructed, and ONE share tampered so the
    accept/reject vector is non-trivial.  The batched verdict must not
    tick celestia_recoveries_total{seam="proof.verify"} when healthy;
    under `verify_fail=1.0` every batched dispatch fails onto the host
    path, which must return the IDENTICAL vector (and the identical
    attestation bytes) while the recovery counter ticks.

    Leg 2 (tampered 502): a malform adversary corrupts shares under
    honest forests — an attestation covering a corrupted coordinate
    must REFUSE (BadProofDetected, the refusal every plane renders
    502) rather than hand out bytes that cannot verify."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.rpc.codec import share_proofs_from_attestation
    from celestia_app_tpu.serve.api import render
    from celestia_app_tpu.serve.sampler import BadProofDetected
    from celestia_app_tpu.serve.verify import verify_proofs
    from celestia_app_tpu.trace.metrics import registry

    def _verify_falls() -> float:
        for labels, val in registry().counter(
            "celestia_recoveries_total", ""
        ).samples():
            if (labels.get("seam") == "proof.verify"
                    and labels.get("outcome") == "degraded"):
                return val
        return 0.0

    def _tampered(payload: dict) -> list:
        forged = dict(payload)
        forged["shares"] = list(payload["shares"])
        raw = bytearray(bytes.fromhex(forged["shares"][0]))
        raw[100] ^= 0xFF  # past the namespace prefix: data corruption
        forged["shares"][0] = raw.hex()
        return share_proofs_from_attestation(forged)

    eds, dah, entry, provider = _adv_square(k, seed=818)
    root = eds.data_root()
    n = 2 * k
    rng = np.random.default_rng(828)
    coords = set()
    while len(coords) < min(samples, n * n):
        r, c = int(rng.integers(0, n)), int(rng.integers(0, n))
        axis = "row" if rng.integers(0, 2) else "col"
        coords.add((r, c, axis))
    spec = ",".join(f"{r}:{c}:{axis}" for r, c, axis in sorted(coords))

    chaos.install("")  # baseline leg: no injection even with env chaos
    t0_ns = time.time_ns()
    try:
        payload = provider.attestation_payload(1, spec)
        base_bytes = render(payload)
        before = _verify_falls()
        base_verdicts = verify_proofs(_tampered(payload), root)
        healthy_falls = _verify_falls() - before

        chaos.install("seed=17,verify_fail=1.0")
        drilled = provider.attestation_payload(1, spec)
        drilled_bytes = render(drilled)
        before = _verify_falls()
        drilled_verdicts = verify_proofs(_tampered(drilled), root)
        fallback_falls = _verify_falls() - before

        chaos.install("seed=13,malform_shares=4")
        adv = chaos.active_adversary()
        bad_r, bad_c = sorted(adv.malformed_coords(1, n))[0]
        try:
            provider.attestation_payload(1, f"{bad_r}:{bad_c},0:0")
            tampered_refused = False
        except BadProofDetected:
            tampered_refused = True
    finally:
        chaos.uninstall()

    return {
        "k": k,
        "samples": len(base_verdicts),
        "attest_bytes": len(base_bytes),
        "bytes_identical": drilled_bytes == base_bytes,
        "verdicts_identical": drilled_verdicts == base_verdicts,
        "rejects": base_verdicts.count(False),
        "healthy_falls": healthy_falls,
        "fallback_falls": fallback_falls,
        "tampered_refused": tampered_refused,
        "ok": (
            drilled_bytes == base_bytes
            and drilled_verdicts == base_verdicts
            and base_verdicts.count(False) == 1
            and healthy_falls == 0
            and fallback_falls >= 1
            and tampered_refused
        ),
        "detection": _detection(t0_ns),
    }


def run_qos_drill(budget: int = 40_960, quantum: int = 1024,
                  shards: int = 8) -> dict:
    """QoS enforcement drill — the observe -> enforce loop's write path.

    One sharded mempool under a $CELESTIA_QOS policy: a spammer
    namespace fires admissions at 10x its rate limit while a whale and
    a small honest tenant submit under theirs (the PR 13 swarm's
    whale + small-tenants + spammer mix, mempool-level).  Invariants:

      * the spammer is throttled (QosThrottled — the refusal every
        plane renders 429 / RESOURCE_EXHAUSTED), honest tenants never;
      * honest tenants' DRR reap share is unchanged by the spam leg:
        the small tenant's reaped set is IDENTICAL, the whale's count
        moves by no more than the spammer's admitted budget share;
      * the per-namespace mempool gauges reconcile EXACTLY across
        shards after every insert / reap / committed-drop / TTL path.
    """
    from celestia_app_tpu import qos
    from celestia_app_tpu.mempool import PriorityMempool
    from celestia_app_tpu.qos import QosThrottled
    from celestia_app_tpu.trace.metrics import registry

    WHALE, SMALL, SPAM = "aa", "bb", "ee"
    # The drill's tenants must OWN their labels: in-suite (the tier-1
    # smoke) the process-level top-N admission set may already be full,
    # which would fold every tenant into `other` and collapse the very
    # fairness arbitration under drill.
    from celestia_app_tpu.trace import square_journal

    square_journal._reset_for_tests()
    saved_q = os.environ.get("CELESTIA_MEMPOOL_QUANTUM")
    os.environ["CELESTIA_MEMPOOL_QUANTUM"] = str(quantum)
    qos.install(f"{SPAM}.tx_rate=5,{SPAM}.tx_burst=10")

    def gauges_reconcile(mp) -> bool:
        """Registry per-namespace gauges == the pool's cross-shard sums
        (drained tenants must read 0, never a stale positive)."""
        truth: dict[str, list[int]] = {}
        for s in mp._shards:
            for lbl, (n, b) in s.ns_depth.items():
                agg = truth.setdefault(lbl, [0, 0])
                agg[0] += n
                agg[1] += b
        for name, col in (("celestia_mempool_namespace_txs", 0),
                          ("celestia_mempool_namespace_size_bytes", 1)):
            fam = registry().get(name)
            if fam is None:
                return False
            for labels, value in fam.samples():
                lbl = labels.get("namespace")
                if lbl in (WHALE, SMALL, SPAM):
                    if value != truth.get(lbl, [0, 0])[col]:
                        return False
        return True

    def leg(spam: bool) -> dict:
        mp = PriorityMempool(ttl_num_blocks=1, shards=shards)
        throttled = {WHALE: 0, SMALL: 0, SPAM: 0}

        def ins(ns, i, size, prio):
            tx = f"{ns}:{i}".encode().ljust(size, b".")
            try:
                mp.insert(tx, prio, 0, ns=ns)
            except QosThrottled:
                throttled[ns] += 1

        # The whale outranks everyone on priority AND oversubscribes the
        # reap budget alone — exactly the mix pure-priority reaping
        # starves small tenants under.
        for i in range(30):
            ins(WHALE, i, 2048, 100)
        for i in range(10):
            ins(SMALL, i, 1024, 1)
        if spam:
            for i in range(100):  # 10x the spammer's burst, immediately
                ins(SPAM, i, 256, 50)
        ok_gauges = gauges_reconcile(mp)
        reaped = mp.reap(budget)
        by_ns = {WHALE: [], SMALL: [], SPAM: []}
        for tx in reaped:
            by_ns[tx.split(b":", 1)[0].decode()].append(tx)
        # Commit the reaped set, then age everything else out (TTL=1):
        # both removal paths must leave the gauges reconciled.
        mp.update(1, reaped)
        ok_gauges = ok_gauges and gauges_reconcile(mp)
        mp.update(2, [])
        ok_gauges = ok_gauges and len(mp) == 0 and gauges_reconcile(mp)
        return {"throttled": throttled, "by_ns": by_ns,
                "gauges_reconcile": ok_gauges}

    try:
        honest = leg(spam=False)
        spammed = leg(spam=True)
    finally:
        qos.uninstall()
        if saved_q is None:
            os.environ.pop("CELESTIA_MEMPOOL_QUANTUM", None)
        else:
            os.environ["CELESTIA_MEMPOOL_QUANTUM"] = saved_q

    spam_admitted_bytes = 100 * 256 - spammed["throttled"][SPAM] * 256
    whale_slack = -(-spam_admitted_bytes // 2048)  # ceil, in whale txs
    small_identical = (
        honest["by_ns"][SMALL] == spammed["by_ns"][SMALL]
    )
    whale_share_held = (
        len(spammed["by_ns"][WHALE])
        >= len(honest["by_ns"][WHALE]) - whale_slack
    )
    out = {
        "spam_throttled": spammed["throttled"][SPAM],
        "honest_throttled": (
            spammed["throttled"][WHALE] + spammed["throttled"][SMALL]
            + honest["throttled"][WHALE] + honest["throttled"][SMALL]
        ),
        "small_reaped": len(spammed["by_ns"][SMALL]),
        "whale_reaped_honest": len(honest["by_ns"][WHALE]),
        "whale_reaped_spam": len(spammed["by_ns"][WHALE]),
        "spam_reaped": len(spammed["by_ns"][SPAM]),
        "small_identical": small_identical,
        "whale_share_held": whale_share_held,
        "gauges_reconcile": (
            honest["gauges_reconcile"] and spammed["gauges_reconcile"]
        ),
    }
    out["ok"] = (
        out["spam_throttled"] >= 80  # ~10x over a 10-token burst
        and out["honest_throttled"] == 0
        and small_identical
        and whale_share_held
        and out["gauges_reconcile"]
        and out["small_reaped"] > 0
    )
    return out


def seam_table_lines(prefixes: tuple[str, ...]) -> list[str]:
    """Exposition lines for the given metric families, straight off the
    registry (the soak's summary-table reader)."""
    from celestia_app_tpu.trace.metrics import registry

    return [
        line for line in registry().render().splitlines()
        if line.startswith(prefixes) and not line.startswith("#")
    ]


def seam_table() -> str:
    """The per-seam injection/recovery counts, straight off the registry."""
    lines = seam_table_lines(("celestia_chaos_injections_total",
                              "celestia_recoveries_total"))
    return "\n".join(lines) or "(no injections fired)"


def _detection_cell(det: dict | None) -> str:
    if det is None:
        return f"{'-':<16} {'-':>6} {'-':>10}"
    blocks = det.get("blocks")
    wall = det.get("wall_ms")
    return (f"{det.get('by') or '-':<16} "
            f"{blocks if blocks is not None else '-':>6} "
            f"{wall if wall is not None else '-':>10}")


def detection_table(rows: list[tuple[str, dict | None]]) -> str:
    """The per-drill time-to-detection summary: which signal noticed the
    injected fault first (SLO page / flight trigger), after how many
    blocks, and after how many wall-ms."""
    out = [f"{'drill':<22} {'detected by':<16} {'blocks':>6} {'wall_ms':>10}"]
    for name, det in rows:
        out.append(f"{name:<22} {_detection_cell(det)}")
    return "\n".join(out)


def write_adv_round(path: str, wd: dict, adv: dict, wall_s: float,
                    heal: dict | None = None,
                    quorum: dict | None = None) -> None:
    """The checked-in ADV_rNN.json shape (bench_trend gates it): the
    measured detection-probability table, the repair-to-recovery
    latency, the always-detected verdicts for the tampering adversaries,
    and — schema adv-v2 — the healing drill's detect-to-restored-service
    legs (single node + quorum), whose invariants bench_trend hard-fails
    and whose total_ms gates lower-better under the same-platform rule."""
    import json

    import jax

    try:
        platform = jax.devices()[0].platform
    except Exception:  # chaos-ok: record the round even with no backend
        platform = "unknown"
    m = re.search(r"ADV_r(\d+)\.json$", os.path.basename(path))
    rec = {
        "n": int(m.group(1)) if m else 1,
        "schema": "adv-v2" if heal is not None else "adv-v1",
        "platform": platform,
        "k": wd["k"],
        "trials": wd["trials"],
        "sample_counts": wd["sample_counts"],
        "detection": wd["detection"],
        "repair": wd["repair"],
        "honest_identical": wd["honest_identical"],
        "all_monotone": wd["all_monotone"],
        "adversaries_detected": {
            "malform": adv["malform"]["ok"],
            "wrong_root": adv["wrong_root"]["ok"],
        },
        "wall_s": round(wall_s, 1),
    }
    if heal is not None:
        rec["heal"] = {
            "single": {
                "k": heal["k"],
                "withhold_frac": heal["withhold_frac"],
                "detect_ms": heal["detect"]["ms"],
                "detect_samples": heal["detect"]["samples"],
                "phases_ms": heal["heal"].get("phases_ms"),
                "heal_total_ms": heal["heal"].get("total_ms"),
                "restored_ms": heal["restored_ms"],
                "healed": heal["heal"].get("outcome") == "healed",
                "served_after_heal": heal["served_after_heal"],
                "root_identical": heal["root_identical"],
                "tampered_never_served": heal["tampered_never_served"],
                "quarantine_outcome": heal["quarantine"].get("outcome"),
            },
        }
        if quorum is not None:
            rec["heal"]["quorum"] = {
                "nodes": quorum["nodes"],
                "k": quorum["k"],
                "withhold_frac": quorum["withhold_frac"],
                "hold_p": quorum["hold_p"],
                "union_coverage": quorum["union_coverage"],
                "detect_ms": [d["ms"] for d in quorum["detections"]],
                "total_ms": quorum["total_ms"],
                "healed": quorum["healed_nodes"] == quorum["nodes"],
                "served_after_heal": quorum["served_after_heal"],
                "root_identical": quorum["root_identical"],
            }
    with open(path, "w") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
        f.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--blocks", type=int, default=20)
    ap.add_argument("--k", type=int, default=16)
    ap.add_argument("--spec", default=DEFAULT_SPEC)
    ap.add_argument("--adv-out", metavar="ADV_rNN.json",
                    help="write the withholding drill's detection-"
                         "probability round record here")
    ap.add_argument("--adv-trials", type=int, default=200,
                    help="withholding drill clients per fraction")
    args = ap.parse_args(argv)

    flight_dir = _arm_flight_recorder()
    print(f"chaos_soak: spec={args.spec!r} flight_dir={flight_dir}",
          flush=True)
    failures = []

    dev = run_device_soak(args.blocks, args.k, args.spec)
    print(f"device soak: {dev['blocks']} blocks @ k={dev['k']} -> "
          f"roots_identical={dev['roots_identical']} "
          f"final_mode={dev['final_mode']} degraded={dev['degraded']}",
          flush=True)
    if not dev["roots_identical"]:
        failures.append(f"device soak diverged: {dev['mismatched_tags']}")

    wal = run_wal_tear_drill(args.spec)
    print(f"WAL tear drill: signed={wal['signed']} "
          f"torn_on_disk={wal['torn_on_disk']} "
          f"salvaged_bytes={wal['salvaged_bytes']} "
          f"conflicts_refused={wal['conflicts_refused']} "
          f"idempotent_resign_ok={wal['idempotent_resign_ok']}", flush=True)
    if not wal["ok"]:
        failures.append(f"WAL drill failed: {wal}")

    smp = run_sampling_drill(k=min(args.k, 8))
    print(f"sampling drill: {smp['samples']} DAS samples @ k={smp['k']} -> "
          f"bit_identical={smp['bit_identical']} "
          f"all_verify={smp['all_verify']} "
          f"injections={smp['injections']:.0f}", flush=True)
    if not smp["ok"]:
        failures.append(f"sampling drill failed: {smp}")

    shd = run_shard_fault_drill(k=min(args.k, 8))
    print(f"shard-fault drill: {shd['samples']} DAS samples @ k={shd['k']} "
          f"shards={shd['shards']} -> "
          + " ".join(
              f"{name}: identical={leg['bit_identical']} "
              f"recoveries={leg['recoveries']:.0f}"
              for name, leg in shd["legs"].items()
          ), flush=True)
    if not shd["ok"]:
        failures.append(f"shard-fault drill failed: {shd}")

    esd = run_extend_shard_drill(k=min(args.k, 8))
    print(f"extend-shard drill: k={esd['k']} shards={esd['shards']} -> "
          f"sharded_identical={esd['sharded_identical']} "
          f"eds_device_shards={esd['eds_device_shards']} "
          f"fault walked_to={esd['walked_to']} "
          f"identical={esd['fault_identical']} "
          f"recoveries={esd['recoveries']:.0f}", flush=True)
    if not esd["ok"]:
        failures.append(f"extend-shard drill failed: {esd}")

    spc = run_speculation_drill(k=min(args.k, 8),
                                blocks=min(args.blocks, 6))
    print(f"speculation drill: {spc['blocks']} blocks @ k={spc['k']} -> "
          f"roots_identical={spc['roots_identical']} hits={spc['hits']:.0f} "
          f"discards={spc['discards']:.0f}", flush=True)
    if not spc["ok"]:
        failures.append(f"speculation drill failed: {spc}")

    bat = run_batched_fault_drill(k=min(args.k, 8),
                                  blocks=min(args.blocks, 6))
    print(f"batched-fault drill: {bat['blocks']} blocks @ k={bat['k']} "
          f"batch={bat['batch']} -> roots_identical={bat['roots_identical']} "
          f"unbatched_falls={bat['unbatched_falls']:.0f} "
          f"final_mode={bat['final_mode']}", flush=True)
    if not bat["ok"]:
        failures.append(f"batched-fault drill failed: {bat}")

    att = run_attestation_drill(k=min(args.k, 8))
    print(f"attestation drill: {att['samples']} samples @ k={att['k']} "
          f"({att['attest_bytes']} attest bytes) -> "
          f"bytes_identical={att['bytes_identical']} "
          f"verdicts_identical={att['verdicts_identical']} "
          f"rejects={att['rejects']} "
          f"fallback_falls={att['fallback_falls']:.0f} "
          f"tampered_refused={att['tampered_refused']}", flush=True)
    if not att["ok"]:
        failures.append(f"attestation drill failed: {att}")

    qd = run_qos_drill()
    print(f"QoS drill: spam_throttled={qd['spam_throttled']} "
          f"honest_throttled={qd['honest_throttled']} "
          f"small_reaped={qd['small_reaped']} "
          f"(identical={qd['small_identical']}) "
          f"whale {qd['whale_reaped_honest']}->{qd['whale_reaped_spam']} "
          f"gauges_reconcile={qd['gauges_reconcile']}", flush=True)
    if not qd["ok"]:
        failures.append(f"QoS drill failed: {qd}")

    t_adv0 = time.monotonic()
    wd = run_withholding_drill(k=min(args.k, 8), trials=args.adv_trials)
    print(f"withholding drill: {wd['trials']} clients x "
          f"{max(wd['sample_counts'])} samples @ k={wd['k']} -> "
          f"monotone={wd['all_monotone']} "
          f"honest_identical={wd['honest_identical']} "
          f"repair_recovered={wd['repair']['recovered']} "
          f"(detect {wd['repair']['detect_ms']} ms + repair "
          f"{wd['repair']['repair_ms']} ms)", flush=True)
    for curve in wd["detection"]:
        print(f"  withhold_frac={curve['withhold_frac']}: "
              f"{curve['p_detect']}", flush=True)
    if not wd["ok"]:
        failures.append(f"withholding drill failed: {wd}")

    adv = run_adversary_detection_drill(k=min(args.k, 8))
    print(f"adversary drill: malform detected={adv['malform']['detected']}/"
          f"{adv['malform']['corrupted_shares']} "
          f"served_invalid={adv['malform']['served_invalid']} "
          f"repair_detected={adv['malform']['repair_detected']}; "
          f"wrong_root detected={adv['wrong_root']['samples_detected']}/"
          f"{adv['wrong_root']['samples_probed']} "
          f"repair_detected={adv['wrong_root']['repair_detected']} "
          f"flight_dumps={adv['flight_dumps']}", flush=True)
    if not adv["ok"]:
        failures.append(f"adversary drill failed: {adv}")

    hd = run_healing_drill(k=min(args.k, 8))
    print(f"healing drill: detect {hd['detect']['samples']} samples / "
          f"{hd['detect']['ms']} ms -> heal "
          f"{hd['heal'].get('total_ms')} ms "
          f"(phases {hd['heal'].get('phases_ms')}) -> restored "
          f"{hd['restored_ms']} ms; served_after_heal="
          f"{hd['served_after_heal']} root_identical={hd['root_identical']} "
          f"tampered_never_served={hd['tampered_never_served']} "
          f"quarantine={hd['quarantine']['outcome']}", flush=True)
    if not hd["ok"]:
        failures.append(f"healing drill failed: {hd}")

    qd = run_quorum_heal_drill(nodes=3, k=min(args.k, 8))
    print(f"quorum heal drill: {qd['nodes']} nodes @ k={qd['k']} "
          f"union={qd['union_coverage']} -> healed_nodes="
          f"{qd['healed_nodes']}/{qd['nodes']} "
          f"detect_ms={[d['ms'] for d in qd['detections']]} "
          f"total={qd['total_ms']} ms served={qd['served_after_heal']} "
          f"roots_identical={qd['root_identical']} "
          f"bundles={qd['heal_bundles']}", flush=True)
    if not qd["ok"]:
        failures.append(f"quorum heal drill failed: {qd}")

    if args.adv_out:
        write_adv_round(args.adv_out, wd, adv, time.monotonic() - t_adv0,
                        heal=hd, quorum=qd)
        print(f"adversary round record -> {args.adv_out}", flush=True)

    gos = run_gossip_drill(args.spec)
    print(f"gossip drill: {gos['sent_unique']} unique msgs converged in "
          f"{gos['rounds']} flood rounds -> {gos['deliveries']} deliveries, "
          f"{gos['unique_delivered']} unique after dedup "
          f"(converged={gos['converged']})", flush=True)
    if not gos["ok"]:
        failures.append(f"gossip drill failed: {gos}")

    brk_epi = run_breaker_drill(k=min(args.k, 8), base_env="epi")
    print(f"breaker drill (epi seat): mode_after={brk_epi['mode_after']} "
          f"health={brk_epi['health_status']} "
          f"roots_identical={brk_epi['roots_identical']} "
          f"paged={brk_epi['paged']} "
          f"detection={brk_epi['detection_blocks']} blocks / "
          f"{brk_epi['detection_wall_ms']} ms", flush=True)
    if not brk_epi["ok"]:
        failures.append(f"breaker drill (epi seat) failed: {brk_epi}")

    brk = run_breaker_drill(k=min(args.k, 8))
    print(f"breaker drill: mode_after={brk['mode_after']} "
          f"health={brk['health_status']} {brk['health_degraded']} "
          f"roots_identical={brk['roots_identical']} "
          f"paged={brk['paged']} "
          f"detection={brk['detection_blocks']} blocks / "
          f"{brk['detection_wall_ms']} ms flight={brk['flight_bundle']}",
          flush=True)
    if not brk["ok"]:
        failures.append(f"breaker drill failed: {brk}")

    print("\nper-seam injection/recovery counts:")
    print(seam_table(), flush=True)

    print("\ntime-to-detection per drill:")
    print(detection_table([
        ("device soak", dev.get("detection")),
        ("WAL tear", wal.get("detection")),
        ("sampling", smp.get("detection")),  # healed by host fallback
        ("extend shard", esd.get("detection")),  # healed by the ladder
        ("speculation", spc.get("detection")),  # discards heal silently
        ("batched fault", bat.get("detection")),
        ("withholding", wd.get("detection_signal")),
        ("adversary", adv.get("detection")),
        ("healing", hd.get("detection")),
        ("quorum heal", qd.get("detection")),
        ("gossip", None),  # healed by redundancy: no anomaly to page on
        ("breaker (epi seat)", brk_epi.get("detection")),
        ("breaker (fused)", brk.get("detection")),
    ]), flush=True)
    flight_lines = seam_table_lines((
        "celestia_flight_dumps_total",
        "celestia_flight_dumps_suppressed_total",
        "celestia_slo_violations_total",
    ))
    if flight_lines:
        print("\npages + flight dumps:")
        print("\n".join(flight_lines), flush=True)

    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print("\nchaos_soak: OK — every drill held correctness under failure")
    return 0


if __name__ == "__main__":
    sys.exit(main())

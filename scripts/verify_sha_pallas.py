"""Standalone TPU drive for the Pallas SHA-256 kernel.

Run on a machine with the real chip (the bench/driver box):

    PYTHONPATH=/root/repo python scripts/verify_sha_pallas.py

It (1) pins the Pallas digests against the fused-jnp path and hashlib for
every message geometry the NMT pipeline uses, across the lane-pad
boundary; (2) times the k=512 NMT+DAH phase with the kernel off and on;
(3) times the full fused pipeline.  Exits non-zero on any mismatch.

This is the TPU-side complement of tests/test_sha_pallas.py (which skips
off-TPU: Pallas has no compiled CPU path and interpret mode is
minutes-slow per geometry).
"""

from __future__ import annotations

import hashlib
import os
import sys
import time

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    print(f"platform: {platform}", flush=True)
    if platform != "tpu":
        print("need the TPU backend; aborting", file=sys.stderr)
        return 2

    from celestia_app_tpu.kernels.sha256 import _sha256_jnp, _sha256_pallas

    rng = np.random.default_rng(7)
    for length in (65, 91, 181, 542):
        for n in (7, 1024, 1030):
            msgs = rng.integers(0, 256, (n, length), dtype=np.uint8)
            want = np.asarray(_sha256_jnp(jnp.asarray(msgs)))
            got = np.asarray(_sha256_pallas(jnp.asarray(msgs)))
            assert np.array_equal(got, want), f"mismatch at L={length} N={n}"
            assert bytes(want[0]) == hashlib.sha256(msgs[0].tobytes()).digest()
    print("equality OK across geometries", flush=True)

    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
    from celestia_app_tpu.da.eds import jit_pipeline, roots_fn
    from celestia_app_tpu.kernels.rs import extend_square_fn

    k = 512
    ods = rng.integers(0, 256, (k * k, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods = ods.reshape(k, k, SHARE_SIZE)
    x = jax.device_put(jnp.asarray(ods))

    def med(fn, arg, iters=5):
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arg))
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    ext = jax.jit(extend_square_fn(k))
    eds = ext(x)
    jax.block_until_ready(eds)

    results = {}
    roots_out = {}
    for flag in ("off", "on"):
        os.environ["CELESTIA_SHA_PALLAS"] = flag
        fn = jax.jit(roots_fn(k))
        out = fn(eds)
        jax.block_until_ready(out)
        roots_out[flag] = [np.asarray(o) for o in out]
        results[flag] = med(fn, eds)
        print(f"nmt_dah sha_pallas={flag}: {results[flag]:.4f}s", flush=True)
    for a, b in zip(roots_out["off"], roots_out["on"]):
        assert np.array_equal(a, b), "roots diverge between sha paths"
    print("roots identical jnp vs pallas", flush=True)

    os.environ.pop("CELESTIA_SHA_PALLAS", None)
    pipe = jit_pipeline(k)
    jax.block_until_ready(pipe(x))
    print(f"full pipeline steady: {med(pipe, x):.4f}s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

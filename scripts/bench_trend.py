#!/usr/bin/env python
"""Bench trajectory reader + regression gate over the BENCH_r*.json rounds.

Each driver round leaves one `BENCH_rNN.json` at the repo root:
`{n, cmd, rc, tail, parsed}` where `tail` is the LAST 2000 bytes of the
bench's stdout — usually ending in the one-line JSON summary bench.py
prints, but possibly truncated at the front (the r04/r05 rounds lose the
`results` array and keep only the trailing `parts`/`stability_pct`
fields) or missing entirely (r01 died before printing).  This tool
reads the whole series, salvages what each round actually recorded, and
prints the per-mode trend table nobody could previously assemble:

    python scripts/bench_trend.py            # table + gate
    python scripts/bench_trend.py --check    # tier-1 self-test mode

The GATE (exit 1) is stability-aware and fires when the newest datapoint
of a gated series drops more than `--threshold` percent (default 10)
plus that round's measured `stability_pct` below the best earlier
datapoint.  Gated by default: the device-resident `compute` rows (the
ROADMAP headline), the batched `repair` rows (compute-bound since the
ISSUE-10 rework; the same-platform prior rule applies), the multi-chip
`compute_sharded<N>` sweep rows (one series PER SHARD COUNT — bench.py
BENCH_MODE=compute_sharded; opt-in like the giant-k rows, so absence
from a default-plan round is a plan gap, never STALE), and the `parts`
decomposition seconds.  The link-bound modes (extend / stream / host)
ride the tunnel between the host and the chip, whose quality varies
between rounds (BENCH_r03's stream row collapsed 13x while compute
improved 24x), so they are REPORTED but only gated under
`--all-series`.  Malformed or empty inputs exit 2 — a bad bench JSON
fails tier-1 fast instead of silently dropping out of the trajectory.

`--metrics-out <dir>` writes the same artifacts bench.py does — a
`bench_trend.prom` Prometheus textfile and `bench_trend.jsonl` rows
(tracer table `bench_trend`) — so the next chip round's numbers land in
the same tables as the live exposition.

The PROOF-SERVING trajectory rides the same gate: any `DAS_rNN.json`
records at the repo root (written by `scripts/das_loadgen.py
--round-out`) contribute a proofs/sec series (gated like a rate, higher
is better) and a proof-p99 series (gated like a parts time, lower is
better), under the same same-platform comparability rule.

The ADVERSARIAL-DRILL trajectory (`ADV_rNN.json`, written by
`scripts/chaos_soak.py --adv-out`) gates differently — it records
INVARIANTS first, latency second:

  * every detection-probability curve must be monotone non-decreasing
    in sample count and the honest leg byte-identical (a violated
    invariant is a hard regression regardless of priors);
  * the tampering adversaries (malform / wrong_root) must have been
    detected on every probe;
  * repair-to-recovery total_ms gates like a parts time (lower better)
    against same-platform priors.

The HEAL series (schema adv-v2, rounds carrying a "heal" block from the
chaos_soak healing drills) extends the same shape: the detect -> repair
-> re-serve loop's invariants gate hard — the heal must complete
(`healed`), the previously-withheld coordinate must serve post-heal
(`served_after_heal`), recovered roots must be bit-identical to the
committed DAH (`root_identical`), tampered state must never have been
served in the heal window (`tampered_never_served`), and the quorum leg
must heal every node — while the single-node and quorum detect-to-
restored latencies (`heal_total_ms` / `total_ms`) gate lower-better
against same-platform priors that also carry a heal block (older
adv-v1 rounds simply predate the loop: additive, never STALE).

The HEIGHT-ANATOMY trajectory (`TL_rNN.json`, written by
`scripts/block_anatomy.py --round-out`) gates SHARES, not seconds: each
`tl.<phase>.share` / `tl.<gap>.gap_share` series is the phase's fraction
of all accounted height time over an N-block streamed run.  The newest
round gates against the best (smallest) same-platform prior share with a
0.05 absolute slack floor — a phase quietly growing its slice of the
height critical path fails `--check` even when every absolute latency
still looks healthy.  Phases a prior round never measured are additive.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The continuous-batching stream rows (bench.py STREAM_BATCHES): B same-k
# squares coalesced into one vmapped dispatch, rate-shaped like every
# other mode.  Gated — the batch-B-vs-batch-1 margin is the feature under
# regression watch — with the same same-platform comparability rule the
# hw-gated parts candidates lean on (a CPU-fallback round's batching
# margin is never compared against a chip round's, and vice versa;
# _comparable_priors drops cross-platform priors for these series too).
STREAM_BATCH_MODES = ("stream_b1", "stream_b2", "stream_b4")
# Modes whose rate is device-resident and comparable across rounds.
# `repair` joined the gated set with the ISSUE-10 batched-repair rework:
# the damaged square ships once and every sweep + the re-extension run
# device-resident, so the row is compute-bound like `compute`, no longer
# dominated by link quality.  `repair_grouped` (the frozen per-pattern-
# group baseline bench.py re-measures at k=128 for the speedup record)
# stays ungated: it exists to be compared against, not to regress.
#
# `mempool_sharded` (bench.py BENCH_MODE=mempool, the concurrent-
# broadcast admission A/B at k=<threads>) gates like a rate under the
# same-platform rule; `mempool_global` — the frozen single-lock baseline
# rung the A/B measures against — stays ungated like repair_grouped: it
# exists to be compared against, not to regress.  Both are opt-in rows
# (only BENCH_MODE=mempool produces them), so absence from a default-
# plan round is a plan gap, never STALE.
GATED_MODES = ("compute", "repair", "mempool_sharded") + STREAM_BATCH_MODES
MEMPOOL_MODES = ("mempool_sharded", "mempool_global")
# The multi-chip extend sweep rows (bench.py BENCH_MODE=compute_sharded,
# kernels/panel_sharded): mode compute_sharded<N>, one series PER SHARD
# COUNT — each N gates against prior rounds carrying the same N under
# the same-platform rule (the das-v2 sweep pattern applied to the write
# side: a 1-shard leg is never a regression against an 8-shard leg).
# Like giant-k rows they are opt-in (only BENCH_MODE=compute_sharded
# produces them), so their absence from a default-plan round is a plan
# gap, never STALE; a shard count no prior round measured is likewise a
# plan gap, not an unknown series.
SHARDED_COMPUTE_RE = re.compile(r"^compute_sharded\d+$")


def is_gated_mode(mode: str) -> bool:
    return mode in GATED_MODES or bool(SHARDED_COMPUTE_RE.match(mode))


# Modes bound by the host<->device link; reported, not gated by default.
LINK_BOUND_MODES = ("extend", "stream", "host")
# The default bench plan stops at k=512 (the paper's north star); rows at
# larger k exist only when a round was driven with BENCH_K=1024/2048 (the
# giant-square frontier).  Such per-k series are LEARNED like any other
# gated series — newest-vs-best-prior under the same-platform rule — but
# their absence from a default-plan round is a plan gap, not staleness:
# the gate must neither cry STALE about a row the plan cannot produce nor
# treat compute@1024 as an unknown series.
DEFAULT_PLAN_MAX_K = 512
# Parts candidates only measured on TPU (the Pallas lowerings): their
# absence from a CPU-fallback round is a platform gap, not a stale series
# — the trend gate must not cry STALE when a chip round simply didn't
# happen.  fused / fused_epi are NOT here: bench measures them on every
# platform (the epilogue rides an XLA composition off-chip), so they are
# never absent — cross-platform comparability is instead handled by the
# regression gate's same-platform rule below.
HW_GATED_PARTS = (
    "rs_dense_pl", "rs_xor", "nmt_dah_pallas", "nmt_dah_plf",
)

# [a-z0-9_]: the stream_b<N> continuous-batching modes carry a digit.
_MODE_ROW_RE = re.compile(r'\{"mode":\s*"[a-z0-9_]+",\s*"k":\s*\d+[^{}]*\}')
_STABILITY_RE = re.compile(r'"stability_pct":\s*([0-9.]+)')
_ERRORS_RE = re.compile(r'"errors":\s*(\[[^\]]*\])')


class MalformedRound(ValueError):
    """A BENCH_r*.json that cannot be read at all (exit 2 material)."""


def _balanced_object(text: str, start: int) -> str | None:
    """The JSON object starting at text[start] == '{', by brace balance
    (good enough here: bench summaries never put braces in strings)."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start : i + 1]
    return None


def _salvage_tail(tail: str) -> dict:
    """Partial recovery from a front-truncated summary line: individual
    mode rows, the parts decomposition, stability, errors."""
    out: dict = {"partial": True}
    rows = []
    for m in _MODE_ROW_RE.finditer(tail):
        try:
            rows.append(json.loads(m.group(0)))
        except ValueError:
            continue
    if rows:
        out["results"] = rows
    i = tail.rfind('"parts": {')
    if i >= 0:
        obj = _balanced_object(tail, i + len('"parts": '))
        if obj is not None:
            try:
                out["parts"] = json.loads(obj)
            except ValueError:
                pass
    m = _STABILITY_RE.search(tail)
    if m:
        out["stability_pct"] = float(m.group(1))
    m = _ERRORS_RE.search(tail)
    if m:
        try:
            out["errors"] = json.loads(m.group(1))
        except ValueError:
            pass
    return out


def _summary_from_tail(tail: str) -> dict | None:
    """The full summary line if the tail still holds it whole."""
    for line in reversed(tail.splitlines()):
        if line.startswith('{"metric"'):
            try:
                return json.loads(line)
            except ValueError:
                return None
    return None


def load_round(path: str) -> dict:
    """One round's recoverable record:

    {round, rc, ok, partial, platform, headline, stability_pct, errors,
     modes: {(mode, k): [mb_per_s, ...]}, parts: {name: seconds} | None,
     tuned: {rs, sha, pipe} | None, applied: {rs, sha, pipe} | None}
    """
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("n", "rc", "tail"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    rec = {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "rc": raw["rc"],
        "ok": raw["rc"] == 0,
        "partial": False,
        "platform": None,
        "headline": None,
        "stability_pct": None,
        "errors": None,
        "modes": {},
        "parts": None,
        "tuned": None,
        "applied": None,
    }
    summary = raw.get("parsed")
    if not isinstance(summary, dict):
        summary = _summary_from_tail(raw["tail"]) if rec["ok"] else None
        if summary is None and rec["ok"]:
            summary = _salvage_tail(raw["tail"])
    if not summary:
        return rec
    rec["partial"] = bool(summary.get("partial"))
    rec["platform"] = summary.get("platform")
    rec["headline"] = summary.get("value")
    rec["stability_pct"] = summary.get("stability_pct")
    rec["errors"] = summary.get("errors")
    for row in summary.get("results", []):
        mode, k = row.get("mode"), row.get("k")
        if mode is None or k is None or "mb_per_s" not in row:
            raise MalformedRound(
                f"{path}: result row missing mode/k/mb_per_s: {row}"
            )
        rec["modes"].setdefault((str(mode), int(k)), []).append(
            float(row["mb_per_s"])
        )
    parts = summary.get("parts")
    if isinstance(parts, dict) and isinstance(parts.get("seconds"), dict):
        rec["parts"] = {
            str(n): float(s) for n, s in parts["seconds"].items()
        }
        for seat_key in ("tuned", "applied"):
            seats = parts.get(seat_key)
            if isinstance(seats, dict):
                rec[seat_key] = {str(a): str(b) for a, b in seats.items()}
    return rec


def load_series(paths: list[str]) -> list[dict]:
    if not paths:
        raise MalformedRound("no BENCH_r*.json files found")
    rounds = sorted((load_round(p) for p in paths), key=lambda r: r["round"])
    if not any(r["modes"] or r["parts"] for r in rounds):
        raise MalformedRound("no round contributed any data")
    return rounds


# --- DAS loadgen rounds (scripts/das_loadgen.py --round-out) -----------------

def load_das_round(path: str) -> dict:
    """One DAS_rNN.json: {n, proofs_per_s, proof_p99_ms, [platform, ...]}.
    Malformed files exit 2 like a bad bench round — a broken loadgen
    record must not silently drop out of the trajectory.

    Swarm rounds (schema "das-v2", das_loadgen --clients) additionally
    carry the shard-count SWEEP (the scaling curve: one row per
    $CELESTIA_SERVE_SHARDS setting over an identical open-loop plan)
    and per-tenant p99/SLO-burn columns; both are validated here so a
    half-written swarm record exits 2 instead of gating on garbage.
    Pre-swarm rounds carry neither — they stay valid as the closed-loop
    workload (see find_das_regressions: workloads never gate each
    other)."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("n", "proofs_per_s", "proof_p99_ms"):
        if key not in raw or raw[key] is None:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    rec = {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "proofs_per_s": float(raw["proofs_per_s"]),
        "proof_p99_ms": float(raw["proof_p99_ms"]),
        "platform": raw.get("platform"),
        "workload": raw.get("workload", "closed"),
        # Which sweep leg produced the headline numbers (swarm rounds):
        # top-level gating is only meaningful between rounds whose
        # headline came from the same mesh width.
        "headline_shards": raw.get("headline_shards"),
        "sweep": {},
        "tenants": {},
    }
    for row in raw.get("sweep") or []:
        for key in ("shards", "proofs_per_s", "proof_p99_ms"):
            if not isinstance(row, dict) or row.get(key) is None:
                raise MalformedRound(
                    f"{path}: sweep row missing {key!r}: {row!r}"
                )
        rec["sweep"][int(row["shards"])] = {
            "proofs_per_s": float(row["proofs_per_s"]),
            "proof_p99_ms": float(row["proof_p99_ms"]),
        }
    for tenant, cols in (raw.get("tenants") or {}).items():
        if not isinstance(cols, dict) or cols.get("slo_burn") is None:
            raise MalformedRound(
                f"{path}: tenant {tenant!r} missing 'slo_burn'"
            )
        # A tenant whose every request FAILED has no latency percentiles
        # (samples==0, failed>0, burn maxed) — that is a valid, honest
        # column; a served tenant without a p99 is malformed.
        all_failed = (
            cols.get("samples") == 0 and (cols.get("failed") or 0) > 0
        )
        if cols.get("p99_ms") is None and not all_failed:
            raise MalformedRound(
                f"{path}: tenant {tenant!r} missing 'p99_ms'"
            )
        if float(cols["slo_burn"]) < 0:
            raise MalformedRound(
                f"{path}: tenant {tenant!r} slo_burn negative"
            )
        rec["tenants"][str(tenant)] = {
            "p99_ms": (
                float(cols["p99_ms"]) if cols.get("p99_ms") is not None
                else None
            ),
            "slo_burn": float(cols["slo_burn"]),
        }
    # The verify-plane block (das_loadgen --attest): batched vs host
    # verified-samples/sec and attestation vs independent bytes-per-
    # sample.  Optional — pre-verify rounds stay valid — but when
    # present every gated column must be there, or the record is as
    # broken as a missing proofs_per_s.
    rec["verify"] = {}
    if raw.get("verify") is not None:
        ver = raw["verify"]
        for key in (
            "verified_per_s_batched", "verified_per_s_host",
            "attest_bytes_per_sample", "independent_bytes_per_sample",
        ):
            if not isinstance(ver, dict) or ver.get(key) is None:
                raise MalformedRound(
                    f"{path}: verify block missing {key!r}"
                )
            rec["verify"][key] = float(ver[key])
    # The fleet block (das_loadgen --urls): the multi-node leg — per-host
    # proofs/sec, the bucket-merged cross-host tail (the same
    # Histogram.merge math GET /fleet serves), end-of-run coverage.
    # Optional — pre-fleet rounds stay valid (das_plan_gaps classifies
    # the first fleet round as a plan gap, never STALE) — but a
    # half-written fleet block exits 2 like any other malformed round.
    rec["fleet"] = None
    if raw.get("fleet") is not None:
        fl = raw["fleet"]
        hosts = fl.get("hosts") if isinstance(fl, dict) else None
        if not isinstance(hosts, list) or len(hosts) < 2:
            raise MalformedRound(
                f"{path}: fleet block needs a 'hosts' list of >= 2 rows"
            )
        for row in hosts:
            for key in ("url", "proofs_per_s", "p99_ms"):
                if not isinstance(row, dict) or row.get(key) is None:
                    raise MalformedRound(
                        f"{path}: fleet host row missing {key!r}: {row!r}"
                    )
        for key in ("cross_host_p50_ms", "cross_host_p99_ms",
                    "coverage_ratio"):
            if fl.get(key) is None:
                raise MalformedRound(
                    f"{path}: fleet block missing {key!r}"
                )
        rec["fleet"] = {
            "hosts": len(hosts),
            # The fleet's aggregate serve rate: hosts ran the identical
            # plan, so the sum is the cluster's measured throughput.
            "proofs_per_s": round(
                sum(float(r["proofs_per_s"]) for r in hosts), 2
            ),
            "cross_host_p50_ms": float(fl["cross_host_p50_ms"]),
            "cross_host_p99_ms": float(fl["cross_host_p99_ms"]),
            "coverage_ratio": float(fl["coverage_ratio"]),
        }
    return rec


def load_das_series(paths: list[str]) -> list[dict]:
    """The proof-serving trajectory; [] when no loadgen round exists yet
    (the series is additive — bench rounds alone stay valid)."""
    return sorted((load_das_round(p) for p in paths), key=lambda r: r["round"])


def _gate_das_points(pts, platforms, key, better, threshold_pct,
                     series: str) -> dict | None:
    """One higher/lower-better gate over a das point list under the
    same-platform rule; None when nothing regressed."""
    if len(pts) < 2:
        return None
    priors = _comparable_priors(pts, platforms)
    if not priors:
        return None
    last_round, last = pts[-1]
    best_prior = max(priors) if better == "higher" else min(priors)
    if best_prior <= 0:
        return None
    worse_pct = (
        (best_prior - last) / best_prior * 100.0
        if better == "higher"
        else (last - best_prior) / best_prior * 100.0
    )
    if worse_pct > threshold_pct:
        return {
            "series": series, "unit": key,
            "round": last_round, "value": last, "best_prior": best_prior,
            "worse_pct": round(worse_pct, 2),
            "allowed_pct": round(threshold_pct, 2),
        }
    return None


def find_das_regressions(das_rounds: list[dict], threshold_pct: float) -> list[dict]:
    """proofs/sec gates like a rate (higher better), proof-p99 like a
    parts time (lower better); same-platform comparability rule as the
    bench series (a CPU loadgen number is not a regression against a
    chip round's).

    Two extra comparability rules for the swarm era:

      * the top-level numbers gate only WITHIN one workload — a swarm
        round's open-loop rate-capped proofs/sec is not a regression
        against a closed-loop round's saturation number (see
        das_plan_gaps: cross-workload absence is a plan gap, not STALE);
      * each SWEEP shard count gates against prior rounds carrying the
        SAME shard count — the scaling curve's rows are their own
        series, and a shard count no prior round measured is a plan
        gap, never a phantom regression.
    """
    platforms = {r["round"]: r.get("platform") for r in das_rounds}
    out = []
    if das_rounds:
        # Top-level comparability key: workload AND the mesh width that
        # produced the headline leg — a 1-shard headline is not a
        # regression against an 8-shard headline any more than a swarm
        # number is against a closed-loop one (the sweep rows below
        # carry the per-shard-count trajectories either way).
        newest_key = (
            das_rounds[-1].get("workload", "closed"),
            das_rounds[-1].get("headline_shards"),
        )
        same = [
            r for r in das_rounds
            if (r.get("workload", "closed"),
                r.get("headline_shards")) == newest_key
        ]
        for key, better in (
            ("proofs_per_s", "higher"), ("proof_p99_ms", "lower")
        ):
            hit = _gate_das_points(
                [(r["round"], r[key]) for r in same], platforms,
                key, better, threshold_pct, f"das.{key}",
            )
            if hit:
                out.append(hit)
        for shards in sorted((das_rounds[-1].get("sweep") or {})):
            comparable = [
                r for r in das_rounds if shards in (r.get("sweep") or {})
            ]
            for key, better in (
                ("proofs_per_s", "higher"), ("proof_p99_ms", "lower")
            ):
                hit = _gate_das_points(
                    [(r["round"], r["sweep"][shards][key])
                     for r in comparable],
                    platforms, key, better, threshold_pct,
                    f"das.sweep{shards}.{key}",
                )
                if hit:
                    out.append(hit)
        # The verify plane (rounds carrying a --attest block): batched
        # verified-samples/sec gates like a rate, attestation bytes-per-
        # sample like a parts time (lower better — the dedup is the
        # point).  Rounds without the block are neither priors nor
        # regressions (plan gap, see das_plan_gaps).
        if das_rounds[-1].get("verify"):
            with_verify = [r for r in das_rounds if r.get("verify")]
            for key, better in (
                ("verified_per_s_batched", "higher"),
                ("attest_bytes_per_sample", "lower"),
            ):
                hit = _gate_das_points(
                    [(r["round"], r["verify"][key]) for r in with_verify],
                    platforms, key, better, threshold_pct,
                    f"das.verify.{key}",
                )
                if hit:
                    out.append(hit)
        # The fleet plane (rounds carrying a --urls block): aggregate
        # cluster proofs/sec gates like a rate, the bucket-merged
        # cross-host p99 like a parts time, and end-of-run coverage
        # like a rate (a coverage collapse means the cluster stopped
        # deciding its squares).  Rounds without the block are neither
        # priors nor regressions (plan gap, see das_plan_gaps); the
        # same-platform rule applies as everywhere else.
        if das_rounds[-1].get("fleet"):
            with_fleet = [r for r in das_rounds if r.get("fleet")]
            for key, better in (
                ("proofs_per_s", "higher"),
                ("cross_host_p99_ms", "lower"),
                ("coverage_ratio", "higher"),
            ):
                hit = _gate_das_points(
                    [(r["round"], r["fleet"][key]) for r in with_fleet],
                    platforms, key, better, threshold_pct,
                    f"das.fleet.{key}",
                )
                if hit:
                    out.append(hit)
    return out


def das_plan_gaps(das_rounds: list[dict]) -> list[str]:
    """Classify what the newest das round does NOT share with its
    priors — workload shapes and sweep shard counts absent from older
    rounds are PLAN GAPS (the plan grew; nothing went stale), mirroring
    the bench series' opt-in/hw-gated classification."""
    if len(das_rounds) < 2:
        return []
    newest = das_rounds[-1]
    priors = das_rounds[:-1]
    gaps = []
    workload = newest.get("workload", "closed")
    if all(r.get("workload", "closed") != workload for r in priors):
        gaps.append(
            f"das workload {workload!r} first measured in "
            f"r{newest['round']:02d} (plan gap, not STALE)"
        )
    elif all(
        (r.get("workload", "closed"), r.get("headline_shards"))
        != (workload, newest.get("headline_shards"))
        for r in priors
    ):
        gaps.append(
            f"das headline shards={newest.get('headline_shards')} first "
            f"measured in r{newest['round']:02d} (plan gap, not STALE)"
        )
    for shards in sorted(newest.get("sweep") or {}):
        if all(shards not in (r.get("sweep") or {}) for r in priors):
            gaps.append(
                f"das sweep shards={shards} first measured in "
                f"r{newest['round']:02d} (plan gap, not STALE)"
            )
    if newest.get("verify") and all(not r.get("verify") for r in priors):
        gaps.append(
            f"das verify plane (--attest) first measured in "
            f"r{newest['round']:02d} (plan gap, not STALE)"
        )
    if newest.get("fleet") and all(not r.get("fleet") for r in priors):
        gaps.append(
            f"das fleet leg (--urls, {newest['fleet']['hosts']} hosts) "
            f"first measured in r{newest['round']:02d} "
            "(plan gap, not STALE)"
        )
    return gaps


# --- adversarial-drill rounds (scripts/chaos_soak.py --adv-out) --------------

def load_adv_round(path: str) -> dict:
    """One ADV_rNN.json: detection-probability table + repair-to-recovery
    + adversary-detected verdicts.  Missing required keys exit 2 like any
    other malformed round."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("n", "detection", "repair", "honest_identical",
                "adversaries_detected"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    return {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "platform": raw.get("platform"),
        "k": raw.get("k"),
        "detection": raw["detection"],
        "repair": raw["repair"],
        "honest_identical": bool(raw["honest_identical"]),
        "all_monotone": bool(raw.get("all_monotone", False)),
        "adversaries_detected": dict(raw["adversaries_detected"]),
        # adv-v2: the healing drill's single-node + quorum legs; None on
        # rounds that predate the detect->act loop (additive series).
        "heal": raw.get("heal"),
    }


def load_adv_series(paths: list[str]) -> list[dict]:
    """[] when no adversarial round exists yet (the series is additive)."""
    return sorted((load_adv_round(p) for p in paths), key=lambda r: r["round"])


def find_adv_regressions(adv_rounds: list[dict], threshold_pct: float) -> list[dict]:
    """Invariants gate hard (no prior needed); repair-to-recovery
    latency gates like a parts time against same-platform priors."""
    out = []
    if not adv_rounds:
        return out
    newest = adv_rounds[-1]
    rnd = newest["round"]
    if not newest["honest_identical"]:
        out.append({
            "series": "adv.honest_identical", "unit": "invariant",
            "round": rnd, "value": False, "best_prior": True,
            "worse_pct": 100.0, "allowed_pct": 0.0,
        })
    if not newest["all_monotone"]:
        out.append({
            "series": "adv.detection_monotone", "unit": "invariant",
            "round": rnd, "value": False, "best_prior": True,
            "worse_pct": 100.0, "allowed_pct": 0.0,
        })
    for name, ok in sorted(newest["adversaries_detected"].items()):
        if not ok:
            out.append({
                "series": f"adv.detected.{name}", "unit": "invariant",
                "round": rnd, "value": False, "best_prior": True,
                "worse_pct": 100.0, "allowed_pct": 0.0,
            })
    if not newest["repair"].get("recovered"):
        out.append({
            "series": "adv.repair_recovered", "unit": "invariant",
            "round": rnd, "value": False, "best_prior": True,
            "worse_pct": 100.0, "allowed_pct": 0.0,
        })
    platforms = {r["round"]: r.get("platform") for r in adv_rounds}

    def _gate_lower_better(series: str, pts: list[tuple[int, float]]) -> None:
        if len(pts) < 2 or pts[-1][0] != rnd:
            return
        priors = _comparable_priors(pts, platforms)
        if not priors:
            return
        best_prior = min(priors)
        last = pts[-1][1]
        if best_prior > 0:
            worse_pct = (last - best_prior) / best_prior * 100.0
            if worse_pct > threshold_pct:
                out.append({
                    "series": series, "unit": "ms",
                    "round": rnd, "value": last,
                    "best_prior": best_prior,
                    "worse_pct": round(worse_pct, 2),
                    "allowed_pct": round(threshold_pct, 2),
                })

    _gate_lower_better("adv.repair_total_ms", [
        (r["round"], float(r["repair"]["total_ms"]))
        for r in adv_rounds
        if r["repair"].get("total_ms") is not None
    ])

    # --- the heal series (schema adv-v2; additive — rounds without a
    # heal block predate the detect->act loop and are neither gated nor
    # STALE) ----------------------------------------------------------------
    heal = newest.get("heal")
    if heal is not None:
        single = heal.get("single") or {}
        for inv in ("healed", "served_after_heal", "root_identical",
                    "tampered_never_served"):
            if not single.get(inv):
                out.append({
                    "series": f"heal.single.{inv}", "unit": "invariant",
                    "round": rnd, "value": False, "best_prior": True,
                    "worse_pct": 100.0, "allowed_pct": 0.0,
                })
        quorum = heal.get("quorum")
        if quorum is not None:
            for inv in ("healed", "served_after_heal", "root_identical"):
                if not quorum.get(inv):
                    out.append({
                        "series": f"heal.quorum.{inv}", "unit": "invariant",
                        "round": rnd, "value": False, "best_prior": True,
                        "worse_pct": 100.0, "allowed_pct": 0.0,
                    })
        _gate_lower_better("heal.single.total_ms", [
            (r["round"], float(r["heal"]["single"]["heal_total_ms"]))
            for r in adv_rounds
            if r.get("heal")
            and (r["heal"].get("single") or {}).get("heal_total_ms")
            is not None
        ])
        _gate_lower_better("heal.quorum.total_ms", [
            (r["round"], float(r["heal"]["quorum"]["total_ms"]))
            for r in adv_rounds
            if r.get("heal")
            and (r["heal"].get("quorum") or {}).get("total_ms") is not None
        ])
    return out


# --- QoS enforcement rounds (scripts/das_loadgen.py --qos-out) ---------------

def load_qos_round(path: str) -> dict:
    """One QOS_rNN.json (schema qos-v1): the swarm harness's whale +
    small-tenants + spammer run under a $CELESTIA_QOS policy — a
    `baseline` leg (no spammer) and a `spam` leg (spammer at a multiple
    of its proof-rate limit) over the SAME open-loop plan, each with
    per-tenant served/throttled/p99/slo_burn columns.  Malformed files
    exit 2 like any other round — a half-written enforcement record must
    not gate on garbage."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("n", "schema", "legs", "spam_tenant"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    legs = raw["legs"]
    for leg in ("baseline", "spam"):
        if not isinstance(legs.get(leg), dict):
            raise MalformedRound(f"{path}: missing leg {leg!r}")
        tenants = legs[leg].get("tenants")
        if not isinstance(tenants, dict) or not tenants:
            raise MalformedRound(f"{path}: leg {leg!r} has no tenants")
        for tenant, cols in tenants.items():
            for col in ("served", "throttled", "slo_burn"):
                if not isinstance(cols, dict) or cols.get(col) is None:
                    raise MalformedRound(
                        f"{path}: leg {leg!r} tenant {tenant!r} missing "
                        f"{col!r}"
                    )
    if raw["spam_tenant"] not in legs["spam"]["tenants"]:
        raise MalformedRound(
            f"{path}: spam_tenant {raw['spam_tenant']!r} absent from the "
            "spam leg's tenant columns"
        )
    return {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "platform": raw.get("platform"),
        "k": raw.get("k"),
        "spam_tenant": str(raw["spam_tenant"]),
        "legs": legs,
    }


def load_qos_series(paths: list[str]) -> list[dict]:
    """[] when no QoS round exists yet (the series is additive)."""
    return sorted((load_qos_round(p) for p in paths), key=lambda r: r["round"])


def find_qos_regressions(qos_rounds: list[dict],
                         threshold_pct: float) -> list[dict]:
    """QoS rounds gate on INVARIANTS of the newest round (no priors
    needed — the enforcement story must hold per round):

      * the spammer was actually throttled (an enforcement record where
        nothing got enforced recorded nothing);
      * every HONEST tenant's SLO burn in the spam leg is no worse than
        its baseline-leg burn (small absolute slack for quantization:
        one violation in a small sample moves burn in steps);
      * every honest tenant's p99 in the spam leg is no worse than
        baseline + the gate threshold (+ a 5 ms absolute floor for
        clock noise on fast samples).
    """
    out = []
    if not qos_rounds:
        return out
    newest = qos_rounds[-1]
    rnd = newest["round"]
    spam_cols = newest["legs"]["spam"]["tenants"][newest["spam_tenant"]]
    if not spam_cols.get("throttled"):
        out.append({
            "series": "qos.spammer_throttled", "unit": "invariant",
            "round": rnd, "value": 0, "best_prior": ">0",
            "worse_pct": 100.0, "allowed_pct": 0.0,
        })
    base = newest["legs"]["baseline"]["tenants"]
    spam = newest["legs"]["spam"]["tenants"]
    for tenant in sorted(set(base) & set(spam)):
        if tenant == newest["spam_tenant"]:
            continue  # the spammer's own numbers are the enforcement
        b, s = base[tenant], spam[tenant]
        burn_ceiling = max(float(b["slo_burn"]) * (1 + threshold_pct / 100),
                           float(b["slo_burn"]) + 0.5)
        if float(s["slo_burn"]) > burn_ceiling:
            out.append({
                "series": f"qos.{tenant}.slo_burn", "unit": "burn",
                "round": rnd, "value": float(s["slo_burn"]),
                "best_prior": float(b["slo_burn"]),
                "worse_pct": round(
                    (float(s["slo_burn"]) - float(b["slo_burn"]))
                    / max(float(b["slo_burn"]), 1e-9) * 100.0, 2),
                "allowed_pct": round(threshold_pct, 2),
            })
        bp, sp = b.get("p99_ms"), s.get("p99_ms")
        if bp is not None and sp is not None:
            # Per-tenant p99 over ~10^2 samples is the single worst
            # observation; the small-sample allowance (2x + 20 ms
            # scheduler-noise floor) keeps the gate about enforcement
            # failures, not about which sample drew the worst timeslice.
            p99_ceiling = max(
                float(bp) * (1 + threshold_pct / 100) + 5.0,
                float(bp) * 2.0 + 20.0,
            )
            if float(sp) > p99_ceiling:
                out.append({
                    "series": f"qos.{tenant}.p99_ms", "unit": "ms",
                    "round": rnd, "value": float(sp),
                    "best_prior": float(bp),
                    "worse_pct": round(
                        (float(sp) - float(bp)) / max(float(bp), 1e-9)
                        * 100.0, 2),
                    "allowed_pct": round(threshold_pct, 2),
                })
    return out


# --- timeline rounds (scripts/block_anatomy.py) ------------------------------

def load_tl_round(path: str) -> dict:
    """One TL_rNN.json (schema tl-v1): the height-anatomy phase budget —
    per-phase / per-gap mean, p95 and share-of-height-time over an
    N-block streamed run, plus critical-phase counts.  The share columns
    are the gated series: a phase quietly growing its slice of height
    time is a regression even when absolute latency stays flat."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("schema", "n", "phases"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    if raw["schema"] != "tl-v1":
        raise MalformedRound(f"{path}: unknown schema {raw['schema']!r}")
    phases = raw["phases"]
    if not isinstance(phases, dict) or not phases:
        raise MalformedRound(f"{path}: 'phases' must be a non-empty dict")
    for name, d in phases.items():
        if not isinstance(d, dict) or "share" not in d:
            raise MalformedRound(
                f"{path}: phase {name!r} carries no 'share' column"
            )
    return {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "platform": raw.get("platform"),
        "k": raw.get("k"),
        "blocks": raw.get("blocks"),
        "phases": phases,
        "gaps": raw.get("gaps") or {},
        "critical_counts": raw.get("critical_counts") or {},
        "total_ms": raw.get("total_ms"),
    }


def load_tl_series(paths: list[str]) -> list[dict]:
    """Timeline rounds sorted by round number; [] when no timeline round
    exists yet (the series is additive)."""
    return sorted((load_tl_round(p) for p in paths),
                  key=lambda r: r["round"])


def find_tl_regressions(tl_rounds: list[dict],
                        threshold_pct: float) -> list[dict]:
    """Gate the newest timeline round's per-phase (and per-gap) share of
    height time against the best same-platform prior.  Shares are
    dimensionless fractions of the run's accounted time, so the gate is
    platform-comparable in a way raw milliseconds are not — but a CPU
    round still only gates against CPU priors, because the critical
    phase itself changes across backends (compile-bound vs drain-bound).
    The 0.05 absolute slack floor keeps sub-5%-share phases from tripping
    the gate on scheduler noise."""
    out: list[dict] = []
    if len(tl_rounds) < 2:
        return out
    newest = tl_rounds[-1]
    priors = [
        r for r in tl_rounds[:-1]
        if r.get("platform") == newest.get("platform")
    ]
    if not priors:
        return out
    rnd = newest["round"]
    for section, label in (("phases", "share"), ("gaps", "gap_share")):
        for name, d in sorted((newest.get(section) or {}).items()):
            value = float(d["share"])
            prior_shares = [
                float(p[section][name]["share"])
                for p in priors
                if name in (p.get(section) or {})
            ]
            if not prior_shares:
                continue  # a NEW phase is growth, not regression
            best = min(prior_shares)
            allowed = best + max(best * threshold_pct / 100.0, 0.05)
            if value > allowed:
                out.append({
                    "series": f"tl.{name}.{label}", "unit": "share",
                    "round": rnd, "value": value, "best_prior": best,
                    "worse_pct": round(
                        (value - best) / max(best, 1e-9) * 100.0, 2),
                    "allowed_pct": round(
                        (allowed - best) / max(best, 1e-9) * 100.0, 2),
                })
    return out


# --- chip-sweep rounds (scripts/chip_sweep.py) -------------------------------

def load_sweep_round(path: str) -> dict:
    """One SWEEP_rNN.json (schema sweep-v1): the push-button standing-
    debt sitting — per-leg status + timing, each leg carrying the
    child's /device snapshot (compile/dispatch ledger + ownership).
    A half-written journal is resumable by chip_sweep --resume, but a
    file this reader cannot parse at all exits 2 like any round."""
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("schema", "round", "plan", "legs"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    if raw["schema"] != "sweep-v1":
        raise MalformedRound(f"{path}: unknown schema {raw['schema']!r}")
    legs = {
        name: {
            "status": rec.get("status", "missing"),
            "seconds": float(rec.get("seconds", 0.0)),
            "device_families": sorted({
                row.get("family", "?")
                for row in (rec.get("device") or {}).get("programs", [])
            }),
        }
        for name, rec in raw["legs"].items()
    }
    return {
        "path": path,
        "round": int(raw["round"]),
        "platform": raw.get("platform", "unprobed"),
        "dryrun": bool(raw.get("dryrun", False)),
        "plan": list(raw["plan"]),
        "legs": legs,
    }


def load_sweep_series(paths: list[str]) -> list[dict]:
    """[] until the first sitting lands (the series is additive)."""
    return sorted(
        (load_sweep_round(p) for p in paths), key=lambda r: r["round"]
    )


def sweep_plan_gaps(sweep_rounds: list[dict]) -> list[str]:
    """What the newest sitting did NOT cover: planned legs that never
    ran ok are COVERAGE GAPS (the debt is still standing for them), and
    legs first appearing in this round are plan gaps like the das
    series' — the plan grew, nothing went stale."""
    if not sweep_rounds:
        return []
    newest = sweep_rounds[-1]
    gaps = []
    if newest["dryrun"]:
        gaps.append(
            f"sweep r{newest['round']:02d} is a dryrun plan — no leg has "
            "paid the standing debt yet"
        )
        return gaps
    for name in newest["plan"]:
        status = newest["legs"].get(name, {}).get("status", "missing")
        if status != "ok":
            gaps.append(
                f"sweep leg {name!r} is {status} in r{newest['round']:02d}"
                " — its standing-debt item is still open"
            )
    priors = [r for r in sweep_rounds[:-1] if not r["dryrun"]]
    if priors:
        for name in newest["plan"]:
            if all(name not in r["plan"] for r in priors):
                gaps.append(
                    f"sweep leg {name!r} first planned in "
                    f"r{newest['round']:02d} (plan gap, not STALE)"
                )
    return gaps


# --- trend assembly ---------------------------------------------------------

def mode_series(rounds: list[dict]) -> dict[tuple[str, int], list[tuple[int, float]]]:
    """{(mode, k): [(round, best mb/s)]} — duplicates within a round (the
    compute@512 stability rerun) collapse to their max."""
    series: dict[tuple[str, int], list[tuple[int, float]]] = {}
    for r in rounds:
        for key, vals in sorted(r["modes"].items()):
            series.setdefault(key, []).append((r["round"], max(vals)))
    return series


def parts_series(rounds: list[dict]) -> dict[str, list[tuple[int, float]]]:
    """{part name: [(round, seconds)]} (lower is better)."""
    series: dict[str, list[tuple[int, float]]] = {}
    for r in rounds:
        for name, secs in sorted((r["parts"] or {}).items()):
            series.setdefault(name, []).append((r["round"], secs))
    return series


def _stability(rounds: list[dict], rnd: int) -> float:
    for r in rounds:
        if r["round"] == rnd:
            return float(r["stability_pct"] or 0.0)
    return 0.0


def _comparable_priors(
    pts: list[tuple[int, float]], platforms: dict[int, str | None]
) -> list[float]:
    """Prior datapoints the newest one may fairly be compared against.

    A CPU-fallback round's numbers are not a regression against a chip
    round's (a fused_epi measured at CPU speed after a TPU round would
    read as a 100x collapse): a prior whose platform is KNOWN and
    DIFFERENT from the newest round's known platform is excluded.
    Unknown platforms (salvaged tails carry none) stay comparable on
    BOTH sides — dropping them would silently weaken the gate for
    exactly the rounds that already lost their results array, and the
    legacy all-priors behavior is what the checked-in r01..r05 series
    were gated under."""
    last_round = pts[-1][0]
    plat = platforms.get(last_round)
    priors = pts[:-1]
    if plat is not None:
        priors = [
            p for p in priors
            if platforms.get(p[0]) in (None, plat)
        ]
    return [v for _, v in priors]


def find_regressions(
    rounds: list[dict],
    threshold_pct: float,
    gate_modes: tuple[str, ...] = GATED_MODES,
    gate_all: bool = False,
) -> list[dict]:
    """Newest datapoint vs best earlier SAME-PLATFORM datapoint per gated
    series (see _comparable_priors); the effective threshold widens by
    the newest round's stability_pct."""
    platforms = {r["round"]: r.get("platform") for r in rounds}
    out = []
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        if not gate_all and not (mode in gate_modes
                                 or SHARDED_COMPUTE_RE.match(mode)):
            continue
        if len(pts) < 2:
            continue
        priors = _comparable_priors(pts, platforms)
        if not priors:
            continue  # nothing measured on this platform before
        last_round, last = pts[-1]
        best_prior = max(priors)
        if best_prior <= 0:
            continue
        allowed = threshold_pct + _stability(rounds, last_round)
        worse_pct = (best_prior - last) / best_prior * 100.0
        if worse_pct > allowed:
            out.append({
                "series": f"{mode}@{k}", "unit": "mb_per_s",
                "round": last_round, "value": last, "best_prior": best_prior,
                "worse_pct": round(worse_pct, 2), "allowed_pct": round(allowed, 2),
            })
    for name, pts in sorted(parts_series(rounds).items()):
        if len(pts) < 2:
            continue
        priors = _comparable_priors(pts, platforms)
        if not priors:
            continue
        last_round, last = pts[-1]
        best_prior = min(priors)
        if best_prior <= 0:
            continue
        allowed = threshold_pct + _stability(rounds, last_round)
        worse_pct = (last - best_prior) / best_prior * 100.0
        if worse_pct > allowed:
            out.append({
                "series": f"parts.{name}", "unit": "seconds",
                "round": last_round, "value": last, "best_prior": best_prior,
                "worse_pct": round(worse_pct, 2), "allowed_pct": round(allowed, 2),
            })
    return out


def seat_changes(rounds: list[dict]) -> list[dict]:
    """Tuned-seat flips between consecutive rounds that recorded a tuner
    verdict.  A flip (e.g. rs rs_dense -> rs_xor) is NEWS, not a fault:
    the >3% hysteresis already demanded a real win, so the trend tool
    names it a seat change — otherwise a newly seated candidate reads as
    a series appearing from nowhere while the dethroned incumbent's
    series looks abandoned."""
    seated = [r for r in rounds if r["tuned"]]
    out = []
    for prev, cur in zip(seated, seated[1:]):
        for key in sorted(set(prev["tuned"]) | set(cur["tuned"])):
            a, b = prev["tuned"].get(key), cur["tuned"].get(key)
            if a is not None and b is not None and a != b:
                out.append({
                    "seat": key, "from": a, "to": b,
                    "from_round": prev["round"], "round": cur["round"],
                })
    return out


def seat_overrides(rounds: list[dict]) -> list[dict]:
    """Seats where the newest round's APPLIED config diverges from its
    tuner pick — an operator-set env knob won over the autotuner (the
    bench honors operator knobs by design).  Worth a line: later rows in
    that round did NOT run the tuner's winner, so its series reflect the
    operator's choice, not the measured-best."""
    for r in reversed(rounds):
        if r["tuned"] and r["applied"]:
            return [
                {"seat": k, "tuned": r["tuned"][k],
                 "applied": r["applied"][k], "round": r["round"]}
                for k in sorted(set(r["tuned"]) & set(r["applied"]))
                if r["tuned"][k] != r["applied"][k]
            ]
    return []


def stale_gated_series(
    rounds: list[dict],
    gate_modes: tuple[str, ...] = GATED_MODES,
    gate_all: bool = False,
) -> list[dict]:
    """Gated series whose newest datapoint predates the newest round that
    recorded ANY data — the gate is comparing stale numbers for them (the
    checked-in compute rows stop at r03 because the r04/r05 tails lost
    the results array).  Reported loudly, not failed: a truncated tail
    must not mask the rounds that DID measure.

    Hardware-gated parts candidates (HW_GATED_PARTS) absent from a
    newest round that did not run on the chip get `hw_gated: True`
    instead: a CPU-fallback round CANNOT measure them, so their absence
    is a platform gap, not a stale series the gate should shout about.

    Giant-k mode rows (k > DEFAULT_PLAN_MAX_K — compute@1024 and
    friends, measured only under an explicit BENCH_K) get `opt_in: True`
    the same way: the default plan never produces them, so their absence
    from a default round is a plan gap.  When two giant-k rounds DO
    exist, find_regressions gates them like any other series under the
    same-platform rule — the downgrade is only about absence.
    """
    newest = max(
        (r["round"] for r in rounds if r["modes"] or r["parts"]), default=None
    )
    if newest is None:
        return []
    newest_rec = next(r for r in rounds if r["round"] == newest)
    # The hw-gated downgrade ("this candidate CANNOT be measured off the
    # chip") only applies when the newest round's platform is KNOWN and
    # non-TPU.  Unknown (a salvaged tail lost the tag) stays on the STALE
    # path: claiming "no chip" for a round that may well have been the
    # chip would hide that the gate is comparing stale chip numbers.
    plat = newest_rec.get("platform")
    newest_known_off_chip = plat is not None and plat != "tpu"
    out = []
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        sharded = bool(SHARDED_COMPUTE_RE.match(mode))
        if not gate_all and not (mode in gate_modes or sharded):
            continue
        if pts[-1][0] < newest:
            entry = {"series": f"{mode}@{k}", "last_round": pts[-1][0],
                     "newest_round": newest}
            if (k > DEFAULT_PLAN_MAX_K or sharded
                    or mode in MEMPOOL_MODES):
                # Opt-in series (explicit BENCH_K / BENCH_MODE=
                # compute_sharded / BENCH_MODE=mempool): absence from a
                # default-plan round is a plan gap, never STALE.
                entry["opt_in"] = True
            out.append(entry)
    for name, pts in sorted(parts_series(rounds).items()):
        if pts[-1][0] < newest:
            entry = {"series": f"parts.{name}", "last_round": pts[-1][0],
                     "newest_round": newest}
            if name in HW_GATED_PARTS and newest_known_off_chip:
                entry["hw_gated"] = True
            out.append(entry)
    return out


def render_table(rounds: list[dict]) -> str:
    """The human trend table: one column per round, one row per series."""
    rnds = [r["round"] for r in rounds]
    lines = []
    header = ["series".ljust(16)] + [f"r{n:02d}".rjust(9) for n in rnds]
    lines.append("  ".join(header))
    modes = mode_series(rounds)

    def fmt_row(label, pts, unit):
        by_round = dict(pts)
        cells = [
            (f"{by_round[n]:9.2f}" if n in by_round else "        -")
            for n in rnds
        ]
        return "  ".join([label.ljust(16)] + cells) + f"  {unit}"

    for mode in GATED_MODES + LINK_BOUND_MODES:
        for (m, k), pts in sorted(modes.items()):
            if m == mode:
                gated = "" if mode in GATED_MODES else " (not gated)"
                lines.append(fmt_row(f"{m}@{k}", pts, f"MB/s{gated}"))
    for (m, k), pts in sorted(modes.items()):
        if m not in GATED_MODES + LINK_BOUND_MODES:
            gated = "" if is_gated_mode(m) else " (not gated)"
            lines.append(fmt_row(f"{m}@{k}", pts, f"MB/s{gated}"))
    for name, pts in sorted(parts_series(rounds).items()):
        lines.append(fmt_row(f"parts.{name}", pts, "s"))
    notes = []
    for r in rounds:
        tags = []
        if not r["ok"]:
            tags.append("FAILED (rc!=0)")
        if r["partial"]:
            tags.append("tail truncated; salvaged")
        if r["errors"]:
            tags.append(f"errors: {'; '.join(map(str, r['errors']))}")
        if r["stability_pct"] is not None:
            tags.append(f"stability ±{r['stability_pct']}%")
        if tags:
            notes.append(f"  r{r['round']:02d}: {', '.join(tags)}")
    if notes:
        lines.append("round notes:")
        lines.extend(notes)
    return "\n".join(lines)


def write_metrics_out(out_dir: str, rounds: list[dict],
                      regressions: list[dict],
                      das_rounds: list[dict] | None = None) -> None:
    """bench_trend.prom + bench_trend.jsonl, the bench.py --metrics-out
    shapes (private registry/tracer: this run's view only)."""
    if REPO_ROOT not in sys.path:  # `python scripts/bench_trend.py` puts
        sys.path.insert(0, REPO_ROOT)  # scripts/, not the repo, on the path
    from celestia_app_tpu.trace.metrics import Registry
    from celestia_app_tpu.trace.tracer import Tracer

    os.makedirs(out_dir, exist_ok=True)
    reg = Registry()
    tracer = Tracer(env_gated=False)
    rate = reg.gauge("celestia_bench_trend_mb_per_s",
                     "per-round bench rate by series")
    secs = reg.gauge("celestia_bench_trend_part_seconds",
                     "per-round parts decomposition seconds")
    reg.counter("celestia_bench_trend_regressions_total",
                "series flagged by the trend gate").inc(len(regressions))
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        for rnd, v in pts:
            rate.set(v, mode=mode, k=str(k), round=f"r{rnd:02d}")
            tracer.write("bench_trend", round=rnd, mode=mode, k=k,
                         mb_per_s=v)
    for name, pts in sorted(parts_series(rounds).items()):
        for rnd, v in pts:
            secs.set(v, part=name, round=f"r{rnd:02d}")
            tracer.write("bench_trend", round=rnd, part=name, seconds=v)
    if das_rounds:
        das = reg.gauge("celestia_bench_trend_das",
                        "per-round DAS loadgen series (proofs/sec, p99 ms; "
                        "swarm sweep rows per shard count)")
        for r in das_rounds:
            das.set(r["proofs_per_s"], series="proofs_per_s",
                    round=f"r{r['round']:02d}")
            das.set(r["proof_p99_ms"], series="proof_p99_ms",
                    round=f"r{r['round']:02d}")
            tracer.write("bench_trend", round=r["round"],
                         proofs_per_s=r["proofs_per_s"],
                         proof_p99_ms=r["proof_p99_ms"])
            for shards, row in sorted((r.get("sweep") or {}).items()):
                das.set(row["proofs_per_s"], series="proofs_per_s",
                        shards=str(shards), round=f"r{r['round']:02d}")
                tracer.write("bench_trend", round=r["round"],
                             shards=shards,
                             proofs_per_s=row["proofs_per_s"],
                             proof_p99_ms=row["proof_p99_ms"])
            for key, value in sorted((r.get("verify") or {}).items()):
                das.set(value, series=f"verify.{key}",
                        round=f"r{r['round']:02d}")
            for key, value in sorted((r.get("fleet") or {}).items()):
                das.set(float(value), series=f"fleet.{key}",
                        round=f"r{r['round']:02d}")
    for reg_row in regressions:
        tracer.write("bench_trend", regression=True, **reg_row)
    with open(os.path.join(out_dir, "bench_trend.prom"), "w") as f:
        f.write(reg.render())
    with open(os.path.join(out_dir, "bench_trend.jsonl"), "w") as f:
        jsonl = tracer.export_jsonl("bench_trend")
        f.write(jsonl + "\n" if jsonl else "")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="bench round JSONs (default: BENCH_r*.json at the repo root)")
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (widened by the "
                         "round's stability_pct)")
    ap.add_argument("--all-series", action="store_true",
                    help="gate the link-bound modes too")
    ap.add_argument("--check", action="store_true",
                    help="tier-1 self-test: parse + gate the checked-in "
                         "rounds, no device needed")
    ap.add_argument("--metrics-out", metavar="DIR",
                    help="write bench_trend.prom + bench_trend.jsonl here")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary instead of the table")
    args = ap.parse_args(argv)

    paths = args.files or sorted(glob.glob(os.path.join(args.dir, "BENCH_r*.json")))
    das_paths = (
        [] if args.files
        else sorted(glob.glob(os.path.join(args.dir, "DAS_r*.json")))
    )
    adv_paths = (
        [] if args.files
        else sorted(glob.glob(os.path.join(args.dir, "ADV_r*.json")))
    )
    qos_paths = (
        [] if args.files
        else sorted(glob.glob(os.path.join(args.dir, "QOS_r*.json")))
    )
    sweep_paths = (
        [] if args.files
        else sorted(glob.glob(os.path.join(args.dir, "SWEEP_r*.json")))
    )
    tl_paths = (
        [] if args.files
        else sorted(glob.glob(os.path.join(args.dir, "TL_r*.json")))
    )
    try:
        rounds = load_series(paths)
        das_rounds = load_das_series(das_paths)
        adv_rounds = load_adv_series(adv_paths)
        qos_rounds = load_qos_series(qos_paths)
        sweep_rounds = load_sweep_series(sweep_paths)
        tl_rounds = load_tl_series(tl_paths)
    except MalformedRound as e:
        print(f"bench_trend: MALFORMED: {e}", file=sys.stderr)
        return 2
    if args.check:
        # Self-test: every round that EXITED cleanly must have contributed
        # data — a bench whose summary line stopped parsing entirely is a
        # tooling regression, not a quiet gap in the table.
        for r in rounds:
            if r["ok"] and not r["modes"] and not r["parts"]:
                print(f"bench_trend: MALFORMED: {r['path']} exited 0 but no "
                      "summary data could be recovered from its tail",
                      file=sys.stderr)
                return 2
    regressions = find_regressions(
        rounds, args.threshold, gate_all=args.all_series
    )
    regressions += find_das_regressions(das_rounds, args.threshold)
    regressions += find_adv_regressions(adv_rounds, args.threshold)
    regressions += find_qos_regressions(qos_rounds, args.threshold)
    regressions += find_tl_regressions(tl_rounds, args.threshold)
    das_gaps = das_plan_gaps(das_rounds)
    sweep_gaps = sweep_plan_gaps(sweep_rounds)
    stale = stale_gated_series(rounds, gate_all=args.all_series)
    seats = seat_changes(rounds)
    overrides = seat_overrides(rounds)
    if args.metrics_out:
        write_metrics_out(args.metrics_out, rounds, regressions, das_rounds)
    if args.json:
        print(json.dumps({
            "rounds": [r["round"] for r in rounds],
            "das_rounds": [r["round"] for r in das_rounds],
            "adv_rounds": [r["round"] for r in adv_rounds],
            "qos_rounds": [r["round"] for r in qos_rounds],
            "sweep_rounds": [r["round"] for r in sweep_rounds],
            "tl_rounds": [r["round"] for r in tl_rounds],
            "sweep_plan_gaps": sweep_gaps,
            "regressions": regressions,
            "stale": [s for s in stale
                      if not s.get("hw_gated") and not s.get("opt_in")],
            "hw_gated": [s for s in stale if s.get("hw_gated")],
            "opt_in": [s for s in stale if s.get("opt_in")],
            "seat_changes": seats,
            "seat_overrides": overrides,
            "das_plan_gaps": das_gaps,
            "threshold_pct": args.threshold,
        }))
    else:
        print(render_table(rounds))
        for r in das_rounds:
            print(f"  das r{r['round']:02d}: "
                  f"{r['proofs_per_s']:9.2f} proofs/s  "
                  f"p99 {r['proof_p99_ms']:8.3f} ms"
                  + (f"  [{r.get('workload', 'closed')}]")
                  + (f"  [{r['platform']}]" if r.get("platform") else ""))
            for shards, row in sorted((r.get("sweep") or {}).items()):
                print(f"    shards={shards}: "
                      f"{row['proofs_per_s']:9.2f} proofs/s  "
                      f"p99 {row['proof_p99_ms']:8.3f} ms")
            if r.get("tenants"):
                worst = max(
                    r["tenants"].items(), key=lambda kv: kv[1]["slo_burn"]
                )
                print(f"    tenants: {len(r['tenants'])}, worst burn "
                      f"{worst[0]}={worst[1]['slo_burn']} "
                      f"(p99 {worst[1]['p99_ms']} ms)")
            if r.get("fleet"):
                fl = r["fleet"]
                print(f"    fleet: {fl['hosts']} hosts "
                      f"{fl['proofs_per_s']:9.2f} proofs/s  "
                      f"cross-host p99 {fl['cross_host_p99_ms']:8.3f} ms  "
                      f"coverage {fl['coverage_ratio']:.4f}")
        for gap in das_gaps:
            print(f"  NOTE: {gap}")
        for r in sweep_rounds:
            ok = sum(1 for leg in r["legs"].values()
                     if leg["status"] == "ok")
            print(f"  sweep r{r['round']:02d}: {ok}/{len(r['plan'])} legs ok"
                  + ("  [dryrun]" if r["dryrun"] else "")
                  + (f"  [{r['platform']}]" if r.get("platform") else ""))
        for gap in sweep_gaps:
            print(f"  NOTE: {gap}")
        for r in qos_rounds:
            spam = r["legs"]["spam"]["tenants"][r["spam_tenant"]]
            honest = [
                t for t in r["legs"]["spam"]["tenants"]
                if t != r["spam_tenant"]
            ]
            worst = max(
                (float(r["legs"]["spam"]["tenants"][t]["slo_burn"])
                 for t in honest),
                default=0.0,
            )
            print(f"  qos r{r['round']:02d}: spammer {r['spam_tenant']} "
                  f"throttled={spam.get('throttled')} "
                  f"served={spam.get('served')}; honest tenants "
                  f"{len(honest)}, worst spam-leg burn {worst}"
                  + (f"  [{r['platform']}]" if r.get("platform") else ""))
        for r in tl_rounds:
            worst = max(
                r["phases"].items(), key=lambda kv: kv[1]["share"],
                default=None,
            )
            crit = r.get("critical_counts") or {}
            crit_s = ", ".join(
                f"{name}x{n}" for name, n in
                sorted(crit.items(), key=lambda kv: -kv[1])
            ) or "-"
            print(f"  tl r{r['round']:02d}: {r.get('blocks', '?')} blocks "
                  f"k={r.get('k', '?')}; top phase "
                  f"{worst[0]}={worst[1]['share'] * 100:.1f}% "
                  f"(mean {worst[1]['mean_ms']} ms); critical {crit_s}"
                  + (f"  [{r['platform']}]" if r.get("platform") else ""))
        for r in adv_rounds:
            rep = r["repair"]
            print(f"  adv r{r['round']:02d}: monotone={r['all_monotone']} "
                  f"honest={r['honest_identical']} "
                  f"detected={r['adversaries_detected']} "
                  f"repair {rep.get('total_ms')} ms "
                  f"(recovered={rep.get('recovered')})"
                  + (f"  [{r['platform']}]" if r.get("platform") else ""))
            heal = r.get("heal")
            if heal:
                single = heal.get("single") or {}
                quorum = heal.get("quorum") or {}
                print(f"    heal: single detect {single.get('detect_ms')} ms"
                      f" + heal {single.get('heal_total_ms')} ms -> restored"
                      f" {single.get('restored_ms')} ms "
                      f"(healed={single.get('healed')}, served="
                      f"{single.get('served_after_heal')})"
                      + (f"; quorum {quorum.get('nodes')} nodes "
                         f"{quorum.get('total_ms')} ms "
                         f"(healed={quorum.get('healed')})"
                         if quorum else ""))
        for c in seats:
            print(f"  SEAT CHANGE: {c['seat']} {c['from']} -> {c['to']} "
                  f"(r{c['from_round']:02d} -> r{c['round']:02d}; the >3% "
                  "hysteresis demanded a real win, so series moving between "
                  "these candidates is expected, not a regression)")
        for o in overrides:
            print(f"  OPERATOR OVERRIDE: {o['seat']} ran {o['applied']} in "
                  f"r{o['round']:02d} though the tuner picked {o['tuned']} — "
                  "that round's later rows reflect the operator's knob")
        for s in stale:
            if s.get("hw_gated"):
                print(f"  hw-gated: {s['series']} not measurable in "
                      f"r{s['newest_round']:02d} (no chip; last chip value "
                      f"r{s['last_round']:02d}) — platform gap, not stale")
            elif s.get("opt_in"):
                print(f"  opt-in: {s['series']} is a giant-k row the "
                      f"default plan never measures (last BENCH_K round "
                      f"r{s['last_round']:02d}) — plan gap, not stale; "
                      "same-platform gating applies when it is measured")
            else:
                print(f"  STALE: gated series {s['series']} last measured in "
                      f"r{s['last_round']:02d} (newest data is "
                      f"r{s['newest_round']:02d}) — the gate compares old "
                      "numbers")
        if regressions:
            print("regressions:")
            for r in regressions:
                print(f"  {r['series']}: r{r['round']:02d} {r['value']} vs "
                      f"best prior {r['best_prior']} ({r['unit']}): worse by "
                      f"{r['worse_pct']}% > allowed {r['allowed_pct']}%")
        else:
            gate = "all series" if args.all_series else "compute + parts"
            print(f"trend gate OK ({gate}, threshold {args.threshold}%"
                  f" + per-round stability)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Bench trajectory reader + regression gate over the BENCH_r*.json rounds.

Each driver round leaves one `BENCH_rNN.json` at the repo root:
`{n, cmd, rc, tail, parsed}` where `tail` is the LAST 2000 bytes of the
bench's stdout — usually ending in the one-line JSON summary bench.py
prints, but possibly truncated at the front (the r04/r05 rounds lose the
`results` array and keep only the trailing `parts`/`stability_pct`
fields) or missing entirely (r01 died before printing).  This tool
reads the whole series, salvages what each round actually recorded, and
prints the per-mode trend table nobody could previously assemble:

    python scripts/bench_trend.py            # table + gate
    python scripts/bench_trend.py --check    # tier-1 self-test mode

The GATE (exit 1) is stability-aware and fires when the newest datapoint
of a gated series drops more than `--threshold` percent (default 10)
plus that round's measured `stability_pct` below the best earlier
datapoint.  Gated by default: the device-resident `compute` rows (the
ROADMAP headline) and the `parts` decomposition seconds.  The
link-bound modes (extend / stream / repair / host) ride the tunnel
between the host and the chip, whose quality varies between rounds
(BENCH_r03's stream row collapsed 13x while compute improved 24x), so
they are REPORTED but only gated under `--all-series`.  Malformed or
empty inputs exit 2 — a bad bench JSON fails tier-1 fast instead of
silently dropping out of the trajectory.

`--metrics-out <dir>` writes the same artifacts bench.py does — a
`bench_trend.prom` Prometheus textfile and `bench_trend.jsonl` rows
(tracer table `bench_trend`) — so the next chip round's numbers land in
the same tables as the live exposition.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Modes whose rate is device-resident and comparable across rounds.
GATED_MODES = ("compute",)
# Modes bound by the host<->device link; reported, not gated by default.
LINK_BOUND_MODES = ("extend", "stream", "repair", "host")

_MODE_ROW_RE = re.compile(r'\{"mode":\s*"[a-z_]+",\s*"k":\s*\d+[^{}]*\}')
_STABILITY_RE = re.compile(r'"stability_pct":\s*([0-9.]+)')
_ERRORS_RE = re.compile(r'"errors":\s*(\[[^\]]*\])')


class MalformedRound(ValueError):
    """A BENCH_r*.json that cannot be read at all (exit 2 material)."""


def _balanced_object(text: str, start: int) -> str | None:
    """The JSON object starting at text[start] == '{', by brace balance
    (good enough here: bench summaries never put braces in strings)."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start : i + 1]
    return None


def _salvage_tail(tail: str) -> dict:
    """Partial recovery from a front-truncated summary line: individual
    mode rows, the parts decomposition, stability, errors."""
    out: dict = {"partial": True}
    rows = []
    for m in _MODE_ROW_RE.finditer(tail):
        try:
            rows.append(json.loads(m.group(0)))
        except ValueError:
            continue
    if rows:
        out["results"] = rows
    i = tail.rfind('"parts": {')
    if i >= 0:
        obj = _balanced_object(tail, i + len('"parts": '))
        if obj is not None:
            try:
                out["parts"] = json.loads(obj)
            except ValueError:
                pass
    m = _STABILITY_RE.search(tail)
    if m:
        out["stability_pct"] = float(m.group(1))
    m = _ERRORS_RE.search(tail)
    if m:
        try:
            out["errors"] = json.loads(m.group(1))
        except ValueError:
            pass
    return out


def _summary_from_tail(tail: str) -> dict | None:
    """The full summary line if the tail still holds it whole."""
    for line in reversed(tail.splitlines()):
        if line.startswith('{"metric"'):
            try:
                return json.loads(line)
            except ValueError:
                return None
    return None


def load_round(path: str) -> dict:
    """One round's recoverable record:

    {round, rc, ok, partial, platform, headline, stability_pct, errors,
     modes: {(mode, k): [mb_per_s, ...]}, parts: {name: seconds} | None}
    """
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedRound(f"{path}: not readable JSON: {e}") from e
    for key in ("n", "rc", "tail"):
        if key not in raw:
            raise MalformedRound(f"{path}: missing required key {key!r}")
    rec = {
        "round": int(raw["n"]),
        "path": os.path.basename(path),
        "rc": raw["rc"],
        "ok": raw["rc"] == 0,
        "partial": False,
        "platform": None,
        "headline": None,
        "stability_pct": None,
        "errors": None,
        "modes": {},
        "parts": None,
    }
    summary = raw.get("parsed")
    if not isinstance(summary, dict):
        summary = _summary_from_tail(raw["tail"]) if rec["ok"] else None
        if summary is None and rec["ok"]:
            summary = _salvage_tail(raw["tail"])
    if not summary:
        return rec
    rec["partial"] = bool(summary.get("partial"))
    rec["platform"] = summary.get("platform")
    rec["headline"] = summary.get("value")
    rec["stability_pct"] = summary.get("stability_pct")
    rec["errors"] = summary.get("errors")
    for row in summary.get("results", []):
        mode, k = row.get("mode"), row.get("k")
        if mode is None or k is None or "mb_per_s" not in row:
            raise MalformedRound(
                f"{path}: result row missing mode/k/mb_per_s: {row}"
            )
        rec["modes"].setdefault((str(mode), int(k)), []).append(
            float(row["mb_per_s"])
        )
    parts = summary.get("parts")
    if isinstance(parts, dict) and isinstance(parts.get("seconds"), dict):
        rec["parts"] = {
            str(n): float(s) for n, s in parts["seconds"].items()
        }
    return rec


def load_series(paths: list[str]) -> list[dict]:
    if not paths:
        raise MalformedRound("no BENCH_r*.json files found")
    rounds = sorted((load_round(p) for p in paths), key=lambda r: r["round"])
    if not any(r["modes"] or r["parts"] for r in rounds):
        raise MalformedRound("no round contributed any data")
    return rounds


# --- trend assembly ---------------------------------------------------------

def mode_series(rounds: list[dict]) -> dict[tuple[str, int], list[tuple[int, float]]]:
    """{(mode, k): [(round, best mb/s)]} — duplicates within a round (the
    compute@512 stability rerun) collapse to their max."""
    series: dict[tuple[str, int], list[tuple[int, float]]] = {}
    for r in rounds:
        for key, vals in sorted(r["modes"].items()):
            series.setdefault(key, []).append((r["round"], max(vals)))
    return series


def parts_series(rounds: list[dict]) -> dict[str, list[tuple[int, float]]]:
    """{part name: [(round, seconds)]} (lower is better)."""
    series: dict[str, list[tuple[int, float]]] = {}
    for r in rounds:
        for name, secs in sorted((r["parts"] or {}).items()):
            series.setdefault(name, []).append((r["round"], secs))
    return series


def _stability(rounds: list[dict], rnd: int) -> float:
    for r in rounds:
        if r["round"] == rnd:
            return float(r["stability_pct"] or 0.0)
    return 0.0


def find_regressions(
    rounds: list[dict],
    threshold_pct: float,
    gate_modes: tuple[str, ...] = GATED_MODES,
    gate_all: bool = False,
) -> list[dict]:
    """Newest datapoint vs best earlier datapoint per gated series; the
    effective threshold widens by the newest round's stability_pct."""
    out = []
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        if not gate_all and mode not in gate_modes:
            continue
        if len(pts) < 2:
            continue
        last_round, last = pts[-1]
        best_prior = max(v for _, v in pts[:-1])
        if best_prior <= 0:
            continue
        allowed = threshold_pct + _stability(rounds, last_round)
        worse_pct = (best_prior - last) / best_prior * 100.0
        if worse_pct > allowed:
            out.append({
                "series": f"{mode}@{k}", "unit": "mb_per_s",
                "round": last_round, "value": last, "best_prior": best_prior,
                "worse_pct": round(worse_pct, 2), "allowed_pct": round(allowed, 2),
            })
    for name, pts in sorted(parts_series(rounds).items()):
        if len(pts) < 2:
            continue
        last_round, last = pts[-1]
        best_prior = min(v for _, v in pts[:-1])
        if best_prior <= 0:
            continue
        allowed = threshold_pct + _stability(rounds, last_round)
        worse_pct = (last - best_prior) / best_prior * 100.0
        if worse_pct > allowed:
            out.append({
                "series": f"parts.{name}", "unit": "seconds",
                "round": last_round, "value": last, "best_prior": best_prior,
                "worse_pct": round(worse_pct, 2), "allowed_pct": round(allowed, 2),
            })
    return out


def stale_gated_series(
    rounds: list[dict],
    gate_modes: tuple[str, ...] = GATED_MODES,
    gate_all: bool = False,
) -> list[dict]:
    """Gated series whose newest datapoint predates the newest round that
    recorded ANY data — the gate is comparing stale numbers for them (the
    checked-in compute rows stop at r03 because the r04/r05 tails lost
    the results array).  Reported loudly, not failed: a truncated tail
    must not mask the rounds that DID measure."""
    newest = max(
        (r["round"] for r in rounds if r["modes"] or r["parts"]), default=None
    )
    if newest is None:
        return []
    out = []
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        if not gate_all and mode not in gate_modes:
            continue
        if pts[-1][0] < newest:
            out.append({"series": f"{mode}@{k}", "last_round": pts[-1][0],
                        "newest_round": newest})
    for name, pts in sorted(parts_series(rounds).items()):
        if pts[-1][0] < newest:
            out.append({"series": f"parts.{name}", "last_round": pts[-1][0],
                        "newest_round": newest})
    return out


def render_table(rounds: list[dict]) -> str:
    """The human trend table: one column per round, one row per series."""
    rnds = [r["round"] for r in rounds]
    lines = []
    header = ["series".ljust(16)] + [f"r{n:02d}".rjust(9) for n in rnds]
    lines.append("  ".join(header))
    modes = mode_series(rounds)

    def fmt_row(label, pts, unit):
        by_round = dict(pts)
        cells = [
            (f"{by_round[n]:9.2f}" if n in by_round else "        -")
            for n in rnds
        ]
        return "  ".join([label.ljust(16)] + cells) + f"  {unit}"

    for mode in GATED_MODES + LINK_BOUND_MODES:
        for (m, k), pts in sorted(modes.items()):
            if m == mode:
                gated = "" if mode in GATED_MODES else " (not gated)"
                lines.append(fmt_row(f"{m}@{k}", pts, f"MB/s{gated}"))
    for (m, k), pts in sorted(modes.items()):
        if m not in GATED_MODES + LINK_BOUND_MODES:
            lines.append(fmt_row(f"{m}@{k}", pts, "MB/s (not gated)"))
    for name, pts in sorted(parts_series(rounds).items()):
        lines.append(fmt_row(f"parts.{name}", pts, "s"))
    notes = []
    for r in rounds:
        tags = []
        if not r["ok"]:
            tags.append("FAILED (rc!=0)")
        if r["partial"]:
            tags.append("tail truncated; salvaged")
        if r["errors"]:
            tags.append(f"errors: {'; '.join(map(str, r['errors']))}")
        if r["stability_pct"] is not None:
            tags.append(f"stability ±{r['stability_pct']}%")
        if tags:
            notes.append(f"  r{r['round']:02d}: {', '.join(tags)}")
    if notes:
        lines.append("round notes:")
        lines.extend(notes)
    return "\n".join(lines)


def write_metrics_out(out_dir: str, rounds: list[dict],
                      regressions: list[dict]) -> None:
    """bench_trend.prom + bench_trend.jsonl, the bench.py --metrics-out
    shapes (private registry/tracer: this run's view only)."""
    if REPO_ROOT not in sys.path:  # `python scripts/bench_trend.py` puts
        sys.path.insert(0, REPO_ROOT)  # scripts/, not the repo, on the path
    from celestia_app_tpu.trace.metrics import Registry
    from celestia_app_tpu.trace.tracer import Tracer

    os.makedirs(out_dir, exist_ok=True)
    reg = Registry()
    tracer = Tracer(env_gated=False)
    rate = reg.gauge("celestia_bench_trend_mb_per_s",
                     "per-round bench rate by series")
    secs = reg.gauge("celestia_bench_trend_part_seconds",
                     "per-round parts decomposition seconds")
    reg.counter("celestia_bench_trend_regressions_total",
                "series flagged by the trend gate").inc(len(regressions))
    for (mode, k), pts in sorted(mode_series(rounds).items()):
        for rnd, v in pts:
            rate.set(v, mode=mode, k=str(k), round=f"r{rnd:02d}")
            tracer.write("bench_trend", round=rnd, mode=mode, k=k,
                         mb_per_s=v)
    for name, pts in sorted(parts_series(rounds).items()):
        for rnd, v in pts:
            secs.set(v, part=name, round=f"r{rnd:02d}")
            tracer.write("bench_trend", round=rnd, part=name, seconds=v)
    for reg_row in regressions:
        tracer.write("bench_trend", regression=True, **reg_row)
    with open(os.path.join(out_dir, "bench_trend.prom"), "w") as f:
        f.write(reg.render())
    with open(os.path.join(out_dir, "bench_trend.jsonl"), "w") as f:
        jsonl = tracer.export_jsonl("bench_trend")
        f.write(jsonl + "\n" if jsonl else "")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="bench round JSONs (default: BENCH_r*.json at the repo root)")
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="regression threshold in percent (widened by the "
                         "round's stability_pct)")
    ap.add_argument("--all-series", action="store_true",
                    help="gate the link-bound modes too")
    ap.add_argument("--check", action="store_true",
                    help="tier-1 self-test: parse + gate the checked-in "
                         "rounds, no device needed")
    ap.add_argument("--metrics-out", metavar="DIR",
                    help="write bench_trend.prom + bench_trend.jsonl here")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable summary instead of the table")
    args = ap.parse_args(argv)

    paths = args.files or sorted(glob.glob(os.path.join(args.dir, "BENCH_r*.json")))
    try:
        rounds = load_series(paths)
    except MalformedRound as e:
        print(f"bench_trend: MALFORMED: {e}", file=sys.stderr)
        return 2
    if args.check:
        # Self-test: every round that EXITED cleanly must have contributed
        # data — a bench whose summary line stopped parsing entirely is a
        # tooling regression, not a quiet gap in the table.
        for r in rounds:
            if r["ok"] and not r["modes"] and not r["parts"]:
                print(f"bench_trend: MALFORMED: {r['path']} exited 0 but no "
                      "summary data could be recovered from its tail",
                      file=sys.stderr)
                return 2
    regressions = find_regressions(
        rounds, args.threshold, gate_all=args.all_series
    )
    stale = stale_gated_series(rounds, gate_all=args.all_series)
    if args.metrics_out:
        write_metrics_out(args.metrics_out, rounds, regressions)
    if args.json:
        print(json.dumps({
            "rounds": [r["round"] for r in rounds],
            "regressions": regressions,
            "stale": stale,
            "threshold_pct": args.threshold,
        }))
    else:
        print(render_table(rounds))
        for s in stale:
            print(f"  STALE: gated series {s['series']} last measured in "
                  f"r{s['last_round']:02d} (newest data is "
                  f"r{s['newest_round']:02d}) — the gate compares old numbers")
        if regressions:
            print("regressions:")
            for r in regressions:
                print(f"  {r['series']}: r{r['round']:02d} {r['value']} vs "
                      f"best prior {r['best_prior']} ({r['unit']}): worse by "
                      f"{r['worse_pct']}% > allowed {r['allowed_pct']}%")
        else:
            gate = "all series" if args.all_series else "compute + parts"
            print(f"trend gate OK ({gate}, threshold {args.threshold}%"
                  f" + per-round stability)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())

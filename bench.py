"""Benchmark: MB/s erasure-extended + DAH-hashed per chip (BASELINE.json metric).

Measures the fused device pipeline (RS 2D extension + 4k NMT roots + DAH data
root; reference hot path app/prepare_proposal.go:61-71) end to end — host
ODS in, data root back on host — at k=128/256/512 plus the repair and
streamed modes, and compares against the in-image host path.

Prints ONE JSON line:
  {"metric": ..., "value": MB/s, "unit": "MB/s", "vs_baseline": x, ...}
extra keys: "platform", "results" (all completed stages), "baseline_note",
"errors".

Robustness (round-1 failure was an unusable accelerator tunnel):
  * the parent process never imports jax; it probes the default backend in a
    subprocess with a hard timeout (SIGTERM, never SIGKILL — killing a
    wedged TPU client can leak the relay's session grant);
  * on probe failure the measurement falls back to a scrubbed CPU env;
  * the measurement child appends one JSON line per completed stage to a
    results file, so even a mid-run hang leaves the earlier numbers intact
    and the parent still emits an honest summary line.

Env knobs:
  BENCH_K            run only this square size (default: 128, 256, 512;
                     giant sizes 1024/2048/4096 are accepted here — the
                     default k-list is unchanged — and scale their own
                     iteration counts / host-RAM prebuild down; a comma
                     list runs a multi-k sweep in one record)
  BENCH_MODE         run only this mode: extend | compute | repair |
                     stream | compute_sharded (the multi-chip extend
                     sweep: one row per BENCH_SHARDS count over an
                     identical sharded-panel plan, kernels/panel_sharded)
                     | mempool (the concurrent-broadcast admission A/B:
                     BENCH_THREADS threads drive a whale+small+spammer
                     tenant mix through PriorityMempool.insert, sharded
                     [$CELESTIA_MEMPOOL_SHARDS stripes] vs the frozen
                     global-lock baseline rung — no device needed)
  BENCH_SHARDS       compute_sharded sweep shard counts (default "1,8")
  BENCH_THREADS      mempool A/B concurrent broadcast threads (default 8)
  BENCH_MEMPOOL_TXS  mempool A/B txs per thread per leg (default 32)
  BENCH_MEMPOOL_ITERS mempool A/B leg repetitions, best-of (default 3)
  BENCH_ITERS        timed iterations (default 5; 2 at k>=256)
  BENCH_BASELINE_S   skip the host-baseline run, use the given seconds/block
  BENCH_TOTAL_BUDGET wall-clock budget in seconds (default 1500)
  BENCH_PROBE_TIMEOUT backend probe timeout in seconds (default 120)

Observability: every completed stage row is also written into the trace
layer's tables (table "bench_rows", the same tracer the serving planes
export over GET /trace_tables), and `--metrics-out <dir>` (or
BENCH_METRICS_OUT) additionally writes `bench_metrics.prom` — a Prometheus
textfile-collector exposition of the per-row rates — plus
`bench_rows.jsonl` next to the BENCH_*.json summary.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))

BASELINE_NOTE = (
    "headline value is the device-resident (`compute`) rate at k=512, the "
    "north-star square size (BASELINE.json). host baseline is the in-image "
    "single-core numpy-GF + hashlib-SHA256 path at k=128; the reference's "
    "Go leopard SIMD + SHA-NI codec is not runnable in this image (no Go "
    "toolchain), so vs_baseline (a rate ratio) overstates the margin vs "
    "the real reference CPU path. The extend/stream/repair modes include "
    "the host<->device link, which in this environment is a network "
    "tunnel of varying quality; the `compute` rows isolate the on-chip "
    "pipeline rate. compute@512 runs twice (stability_pct = spread "
    "between the two medians). Since round 4, every extend iteration "
    "uploads a DISTINCT array — jax dedup-caches repeat transfers of the "
    "same buffer, which previously made extend measure the relay's cache "
    "while stream (distinct buffers) paid the real link; extend and "
    "stream are now like-for-like, and on a serializing tunnel stream's "
    "ceiling is the link rate, not transfer/compute overlap. The compute/"
    "parts/repair rows likewise use a DISTINCT input per timed iteration: "
    "the relay has been observed short-circuiting repeat (executable, "
    "args) executions (a parts run returned 0.0s for a 128 MB-output "
    "program), so reusing one buffer can measure the relay's memo instead "
    "of the chip. The `parts` row decomposes compute@512 into rs_dense / "
    "rs_fft / rs_fft_md / rs_dense_pl (fused Pallas dense, TPU only) / "
    "rs_xor (bitsliced XOR/AND-parity planes, TPU only) and "
    "nmt_dah_{jnp,pallas} device seconds, plus `fused` and `fused_epi` "
    "rows: the single-dispatch extend_and_dah program (kernels/fused, "
    "ODS buffer donated) and its leaf-hash-epilogue variant (the column "
    "extend feeds the bottom half's NMT leaf rounds from VMEM, "
    "kernels/rs_xor), both timed under the tuned RS/SHA picks and A/B'd "
    "against the seated staged extend+hash pair. The parts row "
    "doubles as the autotuner: it runs first and every later row rides "
    "the fastest measured RS and SHA lowerings and the winning "
    "fused-vs-staged pipeline (defaults keep the seat "
    "unless a challenger is >3% faster; the chosen config is recorded in "
    "the parts row's `tuned` field). Stream mode double-buffers with a "
    "dedicated uploader thread and a separate dispatcher (block N+1 "
    "uploads while block N computes), each streamed block a distinct "
    "buffer so relay memoization is never what gets measured. The "
    "stream stage additionally emits stream_b{1,2,4} rows — blocks/sec "
    "with B same-k squares coalesced into ONE vmapped dispatch "
    "($CELESTIA_PIPE_BATCH, cross-height continuous batching): batch-B "
    "seconds/block below the batch-1 row means B squares in one "
    "dispatch cost less than B dispatch latencies."
)


def _random_ods(k: int, seed: int = 3) -> np.ndarray:
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    n = k * k
    ns = np.sort(rng.integers(0, 200, n).astype(np.uint8))
    ods = rng.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    return ods.reshape(k, k, SHARE_SIZE)


# --------------------------------------------------------------------------
# measurement stages (run inside the child process only)
# --------------------------------------------------------------------------


def _median(times: list[float]) -> float:
    return sorted(times)[len(times) // 2]


def _variant(ods: np.ndarray, i: int, axis: int = 1) -> np.ndarray:
    """The i-th distinct input derived from `ods` (i >= 0 never equals the
    warmup array).  Every timed iteration must see a DISTINCT input: jax
    dedup-caches repeat uploads of one buffer, and the tunnel relay has
    been observed short-circuiting repeat (executable, args) executions
    (a parts run returned 0.0s for a 128 MB-output program) — reusing a
    buffer can measure a cache instead of the link or the chip."""
    return np.ascontiguousarray(np.roll(ods, i + 1, axis=axis))


def _extend_seconds(ods: np.ndarray, iters: int) -> float:
    """Full offload round trip: host ODS -> device pipeline -> host data root.

    Every iteration uploads a DISTINCT array: jax dedup-caches repeat
    transfers of the same buffer, which on a tunnel-attached device made
    this row measure the relay's cache instead of the link (round-3
    VERDICT weak #3)."""
    from celestia_app_tpu.da.eds import ExtendedDataSquare

    variants = [_variant(ods, i, axis=0) for i in range(iters)]
    ExtendedDataSquare.compute(ods).data_root()  # warmup / compile
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        ExtendedDataSquare.compute(variants[i]).data_root()
        times.append(time.perf_counter() - t0)
    return _median(times)


def _compute_seconds(ods: np.ndarray, iters: int) -> float:
    """Device-resident pipeline rate: shares already in HBM, full fused
    extend+NMT+DAH program, data root back to host.  Isolates the chip's
    compute from the host link (behind a slow tunnel the link dominates
    `extend`; on PCIe-attached hardware the link is 10+ GB/s and `extend`
    approaches this number).  Median of per-iteration times — round-2's
    driver run recorded a 25x load-induced collapse off a plain 2-iter
    mean, so each iteration is timed separately and the median reported."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.da.eds import jit_pipeline

    k = ods.shape[0]
    pipe = jit_pipeline(k)
    xs = [jax.device_put(jnp.asarray(_variant(ods, i))) for i in range(iters)]
    warm = jax.device_put(jnp.asarray(ods))
    jax.block_until_ready(xs)
    np.asarray(pipe(warm)[3])  # warmup / compile
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        np.asarray(pipe(xs[i])[3])
        times.append(time.perf_counter() - t0)
    return _median(times)


def _sharded_shard_counts() -> list[int]:
    """$BENCH_SHARDS: the compute_sharded sweep's shard counts (default
    "1,8" — the forced-host 1-vs-N machinery curve; real-chip rounds pick
    the mesh widths the hardware has)."""
    raw = os.environ.get("BENCH_SHARDS", "1,8")
    counts = []
    for tok in raw.replace(",", " ").split():
        try:
            n = int(tok)
        except ValueError:
            # Loud, not silent (the CELESTIA_EXTEND_SHARDS convention):
            # a typo'd sweep collapsing to the 1-shard control would
            # read downstream as an opt-in plan gap, hiding the loss.
            print(f"bench: ignoring malformed BENCH_SHARDS entry {tok!r}",
                  file=sys.stderr)
            continue
        if n >= 1:
            counts.append(n)
    return counts or [1]


def _compute_sharded_seconds(ods: np.ndarray, iters: int, shards: int
                             ) -> tuple[float, int]:
    """One compute_sharded sweep leg: seconds/block through the sharded
    panel pipeline at `shards` devices (shards=1 = the single-device
    panel runner, the control every wider leg is judged against).

    The PLAN is identical per shard count — same panel height, same
    DISTINCT per-iteration inputs, same host-driven compute() entry (the
    PR 13 das-v2 sweep pattern applied to the write side) — so the curve
    measures the mesh, not a workload difference.  Returns the ACTUAL
    shard count the seam engaged with (clamped like the serve plane's),
    so rows are keyed by what ran, not what was asked."""
    from celestia_app_tpu.da.eds import ExtendedDataSquare
    from celestia_app_tpu.kernels.fused import pipeline_mode_for_k
    from celestia_app_tpu.kernels.panel_sharded import shards_for_k

    k = ods.shape[0]
    os.environ["CELESTIA_EXTEND_SHARDS"] = (
        str(shards) if shards > 1 else "0"
    )
    actual = shards_for_k(k) or 1
    expect = "sharded_panel" if actual > 1 else "panel"
    mode = pipeline_mode_for_k(k)
    if mode != expect:
        raise RuntimeError(
            f"compute_sharded leg resolved mode {mode!r}, want {expect!r} "
            f"(shards={shards}, actual={actual})"
        )
    variants = [_variant(ods, i) for i in range(iters)]
    ExtendedDataSquare.compute(ods).data_root()  # warmup / compile
    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        ExtendedDataSquare.compute(variants[i]).data_root()
        times.append(time.perf_counter() - t0)
    return _median(times), actual


def _host_seconds_per_block(ods: np.ndarray) -> float:
    """Host path: numpy GF RS extension + hashlib SHA-256 NMT trees.

    Single core (this image has one); stands in for the reference's Go
    leopard + crypto/sha256 path, which is faster — see BASELINE_NOTE.
    """
    from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
    from celestia_app_tpu.gf import codec_for_width
    from celestia_app_tpu.merkle import hash_from_byte_slices
    from celestia_app_tpu.nmt.hasher import NmtHasher

    k = ods.shape[0]
    codec = codec_for_width(k)
    t0 = time.perf_counter()
    row_parity = np.stack([codec.encode(ods[i]) for i in range(k)])
    top = np.concatenate([ods, row_parity], axis=1)  # (k, 2k, S)
    col_parity = np.stack([codec.encode(top[:, j]) for j in range(2 * k)], axis=1)
    eds = np.concatenate([top, col_parity], axis=0)  # (2k, 2k, S)

    parity = PARITY_NAMESPACE_BYTES

    def axis_roots(mat: np.ndarray) -> list[bytes]:
        roots = []
        for i in range(2 * k):
            digests = []
            for j in range(2 * k):
                share = mat[i, j].tobytes()
                in_q0 = i < k and j < k
                ns = share[:NAMESPACE_SIZE] if in_q0 else parity
                digests.append(NmtHasher.hash_leaf(ns + share))
            while len(digests) > 1:
                digests = [
                    NmtHasher.hash_node(digests[t], digests[t + 1])
                    for t in range(0, len(digests), 2)
                ]
            roots.append(digests[0])
        return roots

    row_roots = axis_roots(eds)
    col_roots = axis_roots(eds.transpose(1, 0, 2))
    hash_from_byte_slices(row_roots + col_roots)
    return time.perf_counter() - t0


def _parts_seconds(ods: np.ndarray, iters: int) -> dict:
    """Decomposition of the fused pipeline at one k: device-resident times
    for the RS extension under all three encode lowerings (dense generator
    matmul, additive-FFT stage groups, transpose-free FFT) and for the
    NMT+DAH hashing half under both SHA paths (fused-jnp vs Pallas).

    Doubles as the AUTOTUNER: the returned dict carries a "tuned" entry
    naming the fastest RS and SHA variants; the bench child applies those
    to every later stage, so the headline compute rows always ride the
    best lowering this chip measured (a >3% margin is required to leave
    the defaults — noise must not flip the config)."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.da.eds import roots_fn
    from celestia_app_tpu.kernels.rs import extend_square_fn

    k = ods.shape[0]
    x = jax.device_put(jnp.asarray(ods))
    xs = [jax.device_put(jnp.asarray(_variant(ods, i))) for i in range(iters)]
    out: dict[str, float] = {}
    eds = None
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        on_tpu = False
    saved = {
        var: os.environ.get(var)
        for var in ("CELESTIA_RS_FFT", "CELESTIA_RS_FFT_MD",
                    "CELESTIA_RS_PALLAS", "CELESTIA_RS_XOR")
    }
    try:
        # Each variant builds a FRESH jax.jit around extend_square_fn, so
        # the env flags are re-read at trace time (the lru-cached module
        # wrappers key on (k, construction) only and must not be used for
        # an A/B like this — they would serve the first trace twice).
        variants = [
            ("rs_fft", {"CELESTIA_RS_FFT": "on", "CELESTIA_RS_FFT_MD": ""}),
            ("rs_fft_md", {"CELESTIA_RS_FFT": "on", "CELESTIA_RS_FFT_MD": "1"}),
            ("rs_dense", {"CELESTIA_RS_FFT": "off", "CELESTIA_RS_FFT_MD": ""}),
        ]
        if on_tpu:  # the Pallas kernels have no compiled CPU path
            from celestia_app_tpu.gf.rs import codec_for_width
            from celestia_app_tpu.kernels.rs_pallas import pallas_supported
            from celestia_app_tpu.kernels.rs_xor import xor_supported

            m_field = codec_for_width(k).field.m
            if pallas_supported(k, m_field):
                variants.append(
                    ("rs_dense_pl",
                     {"CELESTIA_RS_FFT": "off", "CELESTIA_RS_FFT_MD": "",
                      "CELESTIA_RS_PALLAS": "on"}))
            if xor_supported(k, m_field):
                variants.append(
                    ("rs_xor",
                     {"CELESTIA_RS_FFT": "off", "CELESTIA_RS_FFT_MD": "",
                      "CELESTIA_RS_XOR": "on"}))
        for label, flags in variants:
            os.environ.pop("CELESTIA_RS_PALLAS", None)
            os.environ.pop("CELESTIA_RS_XOR", None)
            for var, val in flags.items():
                if val:
                    os.environ[var] = val
                else:
                    os.environ.pop(var, None)
            # Per-candidate guard: an opt-in kernel that fails to COMPILE
            # on this chip (the Pallas candidates are exactly the ones
            # unmeasured on hardware) must cost its own row, not the
            # whole parts stage — the incumbents' times and the autotune
            # seat survive.  rs_dense is the incumbent and must raise.
            try:
                fn = jax.jit(extend_square_fn(k))
                eds = fn(x)
                jax.block_until_ready(eds)
                times = []
                for i in range(iters):
                    t0 = time.perf_counter()
                    jax.block_until_ready(fn(xs[i]))
                    times.append(time.perf_counter() - t0)
                out[label] = _median(times)
            except Exception as e:  # noqa: BLE001 — challenger-only tolerance
                if label == "rs_dense":
                    raise
                out[f"{label}_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        # Restore even when a stage raises: a leaked =on would silently
        # flip every later bench stage onto the non-default FFT path.
        for var, val in saved.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val
    # SHA A/B over the hashing half.  Distinct EDS per iteration (extend
    # the distinct inputs on the restored default path), produced one at a
    # time so only one extra (2k,2k,S) square is ever live in HBM
    # alongside the one being hashed.  Release the warmup square and the
    # A/B input before the loop.
    del eds
    del x
    ext = jax.jit(extend_square_fn(k))
    sha_rows = [("nmt_dah_jnp", {"CELESTIA_SHA_PALLAS": "off",
                                 "CELESTIA_SHA_FUSED": "off"})]
    if on_tpu:  # the Pallas kernels have no compiled CPU path
        sha_rows.append(("nmt_dah_pallas", {"CELESTIA_SHA_PALLAS": "on",
                                            "CELESTIA_SHA_FUSED": "off"}))
        # plf: fused-leaf kernel (message construction in VMEM) for the
        # leaf level + the lane-parallel kernel for node levels.
        sha_rows.append(("nmt_dah_plf", {"CELESTIA_SHA_PALLAS": "on",
                                         "CELESTIA_SHA_FUSED": "on"}))
    saved_sha = {v: os.environ.get(v)
                 for v in ("CELESTIA_SHA_PALLAS", "CELESTIA_SHA_FUSED")}
    try:
        for row_i, (label, flags) in enumerate(sha_rows):
            os.environ.update(flags)
            hash_fn = jax.jit(roots_fn(k))
            # Warm on an input DISTINCT from every timed xs[i] (base past
            # the timed range, one per row) — warming on xs[0] would make
            # iteration 0 a repeat (executable, args) pair for the relay
            # memo, the exact hazard _variant documents.
            warm_x = jax.device_put(jnp.asarray(_variant(ods, iters + row_i)))
            warm_eds = ext(warm_x)
            jax.block_until_ready(hash_fn(warm_eds))
            del warm_eds, warm_x
            times = []
            for i in range(iters):
                eds_i = ext(xs[i])
                jax.block_until_ready(eds_i)
                t0 = time.perf_counter()
                jax.block_until_ready(hash_fn(eds_i))
                times.append(time.perf_counter() - t0)
                del eds_i
            out[label] = _median(times)
    finally:
        _apply_env(saved_sha)
    out["nmt_dah"], tuned = _pick_tuned(out, on_tpu)
    # Fused single-dispatch candidates: the whole extend+NMT+DAH program
    # as ONE executable with the ODS buffer donated (kernels/fused) plus
    # its leaf-hash-epilogue variant (fused_epi), both timed under the
    # tuner's RS/SHA picks so the A/B against the seated staged pair is
    # like-for-like.  A fused-only fault must not discard the completed
    # staged rows, so each degrades to a note instead of raising.
    try:
        out["fused"] = _fused_seconds(ods, iters, tuned)
        try:
            out["fused_epi"] = _fused_seconds(ods, iters, tuned,
                                              epilogue=True)
        except Exception as e:  # noqa: BLE001 — epi is optional, fused is not
            out["fused_epi_error"] = f"{type(e).__name__}: {e}"[:200]
        tuned["pipe"] = _pick_pipe(out, tuned)
    except Exception as e:  # noqa: BLE001 — keep the staged measurement
        out["fused_error"] = f"{type(e).__name__}: {e}"[:200]
    out["tuned"] = tuned
    return out


_TUNE_VARS = (
    "CELESTIA_RS_FFT", "CELESTIA_RS_FFT_MD", "CELESTIA_RS_PALLAS",
    "CELESTIA_RS_XOR", "CELESTIA_SHA_PALLAS", "CELESTIA_SHA_FUSED",
    "CELESTIA_PIPE_FUSED",
)


def _env_for_tuned(tuned: dict) -> dict:
    """Env assignment that makes the library run the tuner's picks.

    Values of None mean "remove the var".  Shared by the in-parts fused
    timing and the child's apply step so the two can never disagree about
    what a pick means."""
    env: dict = {"CELESTIA_RS_FFT": "off", "CELESTIA_RS_FFT_MD": None,
                 "CELESTIA_RS_PALLAS": None, "CELESTIA_RS_XOR": None}
    if tuned["rs"] in ("rs_fft", "rs_fft_md"):
        env["CELESTIA_RS_FFT"] = "on"
        if tuned["rs"] == "rs_fft_md":
            env["CELESTIA_RS_FFT_MD"] = "1"
    elif tuned["rs"] == "rs_dense_pl":
        env["CELESTIA_RS_PALLAS"] = "on"
    elif tuned["rs"] == "rs_xor":
        env["CELESTIA_RS_XOR"] = "on"
    env["CELESTIA_SHA_PALLAS"] = (
        "on" if tuned["sha"] in ("pallas", "plf") else "off"
    )
    env["CELESTIA_SHA_FUSED"] = "on" if tuned["sha"] == "plf" else "off"
    if "pipe" in tuned:
        env["CELESTIA_PIPE_FUSED"] = {
            "staged": "off", "fused_epi": "epi"
        }.get(tuned["pipe"], "on")
    return env


def _applied_from_env() -> dict:
    """What the library will ACTUALLY run under the current env — the
    inverse of _env_for_tuned after operator-set knobs are honored.  The
    child's tuned-applied record and the seat-application regression
    tests both call this, so the two directions of the mapping can never
    fork (bench.py:350's shared-mapping contract, extended to rs_xor and
    the fused_epi pipe seat)."""
    fft_env = os.environ.get("CELESTIA_RS_FFT", "auto")
    if fft_env == "on":
        rs = (
            "rs_fft_md"
            if os.environ.get("CELESTIA_RS_FFT_MD") == "1"
            else "rs_fft"
        )
    elif os.environ.get("CELESTIA_RS_PALLAS") == "on":
        rs = "rs_dense_pl"
    elif os.environ.get("CELESTIA_RS_XOR") == "on":
        rs = "rs_xor"
    else:
        rs = "rs_dense"
    sha_env = os.environ.get("CELESTIA_SHA_PALLAS", "auto")
    sha = {"on": "pallas", "off": "jnp"}.get(sha_env, "auto")
    if sha == "pallas" and os.environ.get("CELESTIA_SHA_FUSED") == "on":
        sha = "plf"
    pipe = {"off": "staged", "epi": "fused_epi"}.get(
        os.environ.get("CELESTIA_PIPE_FUSED", "auto"), "fused"
    )
    return {"rs": rs, "sha": sha, "pipe": pipe}


def _apply_env(env: dict) -> None:
    for var, val in env.items():
        if val is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = val


def _fused_seconds(
    ods: np.ndarray, iters: int, tuned: dict, epilogue: bool = False
) -> float:
    """Device seconds for the fused extend_and_dah program with the ODS
    donated (epilogue=True times the leaf-hash-epilogue variant — the
    fused_epi pipe candidate).  Fresh jax.jit (not the lru-cached module
    wrapper) so the tuned env flags are re-read at trace time; a DISTINCT
    pre-uploaded input per iteration (donation consumes each buffer,
    which also keeps the relay memo hazard away — see _variant)."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.kernels.fused import (
        _silence_unusable_donation_warning,
        extend_and_dah_fn,
    )

    k = ods.shape[0]
    _silence_unusable_donation_warning()  # CPU: donation noise, not signal
    saved = {v: os.environ.get(v) for v in _TUNE_VARS}
    try:
        _apply_env(_env_for_tuned(tuned))
        fn = jax.jit(
            extend_and_dah_fn(k, epilogue=epilogue), donate_argnums=(0,)
        )
        warm = jax.device_put(jnp.asarray(_variant(ods, iters)))
        jax.block_until_ready(fn(warm))  # warmup / compile (consumes warm)
        times = []
        for i in range(iters):
            x = jax.device_put(jnp.asarray(_variant(ods, i)))
            jax.block_until_ready(x)
            t0 = time.perf_counter()
            out = fn(x)
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
            del out  # one EDS live at a time
        return _median(times)
    finally:
        _apply_env(saved)


def _pick_pipe(seconds: dict, tuned: dict) -> str:
    """Pipeline seat with the same >3% hysteresis as _pick_tuned.

    The fused single-dispatch program is the incumbent (the library
    default); the staged extend+hash pair — at its own tuned-best RS and
    SHA lowerings — must beat it by >3% to take the seat, and the
    leaf-hash-epilogue variant (fused_epi) must then beat whichever of
    those holds it by the same margin.  Challenger order is fixed, so a
    noise-level three-way tie always resolves to the incumbent."""
    staged = seconds[tuned["rs"]] + seconds["nmt_dah"]
    best, best_s = "fused", seconds["fused"]
    if staged < 0.97 * best_s:
        best, best_s = "staged", staged
    epi = seconds.get("fused_epi")
    if epi is not None and epi < 0.97 * best_s:
        best = "fused_epi"
    return best


def _pick_tuned(seconds: dict, on_tpu: bool) -> tuple[float, dict]:
    """Winner selection with hysteresis over a parts measurement.

    The incumbents — rs_dense, and the path sha auto would pick on this
    platform (Pallas on TPU, jnp elsewhere) — keep the seat unless a
    challenger is >3% faster, so measurement noise cannot flip the
    config.  Returns (nmt_dah headline seconds — the tuner's SHA pick;
    the child's "tuned-applied" record says what later rows actually ran
    once operator-set knobs are honored, tuned choices dict)."""
    rs_best = "rs_dense"
    for label in ("rs_fft", "rs_fft_md", "rs_dense_pl", "rs_xor"):
        if label in seconds and seconds[label] < 0.97 * seconds[rs_best]:
            rs_best = label
    sha_best = "pallas" if on_tpu else "jnp"
    for label in ("jnp", "plf"):
        key = f"nmt_dah_{label}"
        if key in seconds and seconds[key] < 0.97 * seconds[f"nmt_dah_{sha_best}"]:
            sha_best = label
    return seconds[f"nmt_dah_{sha_best}"], {"rs": rs_best, "sha": sha_best}


def _repair_seconds(ods: np.ndarray, iters: int) -> float:
    """BASELINE config 4: quadrant erasure -> repair -> verified roots."""
    import jax

    from celestia_app_tpu.da import DataAvailabilityHeader, ExtendedDataSquare, repair

    k = ods.shape[0]
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False  # 25% missing

    def damaged_case(o: np.ndarray):
        eds = ExtendedDataSquare.compute(o)
        dah = DataAvailabilityHeader.from_eds(eds)
        full = np.asarray(eds.squared())
        return np.where(present[..., None], full, 0).astype(np.uint8), dah

    warm_damaged, warm_dah = damaged_case(ods)
    repair(warm_damaged, present, warm_dah)  # warmup
    del warm_damaged
    # Distinct (square, DAH) per timed iteration (see _variant), built one
    # at a time so host residency stays at one damaged square; median of
    # per-iteration times like the other rows.
    times = []
    for i in range(iters):
        damaged, dah = damaged_case(_variant(ods, i))
        t0 = time.perf_counter()
        repair(damaged, present, dah)
        jax.effects_barrier()
        times.append(time.perf_counter() - t0)
        del damaged
    return _median(times)


def _stream_block_budget(ods: np.ndarray, iters: int) -> tuple[int, int]:
    """(timed blocks, warm blocks) the stream stages may prebuild under
    the ~1.5 GB host-RAM cap.  The old fixed floor of 4 blocks OVERRAN
    the cap at giant k (4 x 550 MB at k=1024); now the block count
    scales down with the square size — to a floor of one timed block and
    one warm block, the least a stream can stream."""
    cap = int(1.5e9 // ods.nbytes)
    n = max(1, min(4 * iters, cap if cap >= 1 else 1))
    return n, (2 if n >= 4 else 1)


def _stream_seconds(ods: np.ndarray, iters: int) -> float:
    """BASELINE config 5: pipelined block stream — double-buffered async
    dispatch.  The pipeline's uploader thread transfers block i+1 while
    the device computes block i (a separate dispatcher thread keeps the
    upload lane free of dispatch round-trips), so steady state approaches
    max(transfer, compute) instead of their sum, and with the fused
    lowering each uploaded ODS buffer is donated to its dispatch."""
    from celestia_app_tpu.parallel.pipeline import stream_blocks

    k = ods.shape[0]

    # Every streamed block is DISTINCT (see _variant): a cyclic reuse of a
    # few buffers would repeat (executable, args) pairs that the relay
    # memo can short-circuit, understating the link cost.  All variants
    # are materialized BEFORE the timed window so the feeder never charges
    # host roll/copy work to the stream measurement (device timings
    # collapse badly under concurrent host load on this box).  Prebuilt
    # bytes are capped at ~1.5 GB host RAM (a manual BENCH_K=512 stream
    # would otherwise resident 4*iters 134 MB squares at once); at giant
    # k the cap SCALES THE BLOCK COUNT DOWN (floor 1 — one ODS must
    # exist to stream) instead of overrunning it with a fixed minimum.
    n, warm_n = _stream_block_budget(ods, iters)
    warm_blocks = [_variant(ods, n + i, axis=0) for i in range(warm_n)]
    blocks = [_variant(ods, i, axis=0) for i in range(n)]

    def feed(blist):
        for i, b in enumerate(blist):
            yield i, b

    list(stream_blocks(feed(warm_blocks), k, depth=2))  # warm the pipeline
    t0 = time.perf_counter()
    for _tag, eds in stream_blocks(feed(blocks), k, depth=2):
        eds.data_root()  # host sync per block, as a server would
    return (time.perf_counter() - t0) / n


#: Coalesced-dispatch sizes the batched stream row measures (the
#: continuous-batching leg: B same-k squares in ONE vmapped dispatch
#: instead of B dispatch latencies).
STREAM_BATCHES = (1, 2, 4)


def _stream_batched_seconds(ods: np.ndarray, iters: int) -> dict[int, float]:
    """Seconds per block streamed at each coalescing size in
    STREAM_BATCHES — the blocks/sec face of cross-height continuous
    batching.  Same distinct-buffer and prebuilt-variant rules as
    _stream_seconds; depth widens with the batch so the coalescer always
    has queued squares to merge (otherwise the occupancy signal would
    close every batch at 1 and the row would measure nothing)."""
    from celestia_app_tpu.parallel.pipeline import stream_blocks

    k = ods.shape[0]
    n, _ = _stream_block_budget(ods, iters)
    n -= n % max(STREAM_BATCHES)  # same block count for every batch size
    if n < max(STREAM_BATCHES):
        # Giant k: the RAM cap scaled the stream below one full batch —
        # a coalescing measurement would be fiction (and the vmapped
        # batched program would materialize B giant EDSes).  The caller
        # emits no stream_b rows; batching giant squares is not a thing.
        return {}
    blocks = [_variant(ods, i, axis=0) for i in range(n)]
    warm_blocks = [_variant(ods, n + i, axis=0) for i in range(max(STREAM_BATCHES))]

    def feed(blist):
        for i, b in enumerate(blist):
            yield i, b

    # AOT-compile EVERY coalescing size 1..max up front: _coalesce is
    # opportunistic (it merges whatever happens to be queued), so a warm
    # STREAM alone cannot guarantee which batch executables get built —
    # and a multi-second jax compile landing inside the timed window
    # would corrupt a series bench_trend gates.  warmup() builds the
    # owned-input batched programs directly, race-free.
    from celestia_app_tpu.da.eds import warmup as _warmup

    _warmup(square_sizes=[k],
            batches=tuple(range(2, max(STREAM_BATCHES) + 1)))
    out: dict[int, float] = {}
    for batch in STREAM_BATCHES:
        depth = max(2, batch)
        # One warm stream per size on top of the AOT compiles: primes the
        # pipeline threads and any remaining first-dispatch cost.
        list(stream_blocks(feed(warm_blocks), k, depth=depth, batch=batch))
        t0 = time.perf_counter()
        for _tag, eds in stream_blocks(feed(blocks), k, depth=depth,
                                       batch=batch):
            eds.data_root()  # host sync per block, as a server would
        out[batch] = (time.perf_counter() - t0) / n
    return out


# --------------------------------------------------------------------------
# the mempool admission A/B (BENCH_MODE=mempool; no device, no jax)
# --------------------------------------------------------------------------


def _mempool_tx_sets(threads: int, per_thread: int):
    """One tenant per thread — whale (2 MiB txs), small tenants
    (512 KiB), one spammer (16 KiB) — with unique tx bytes, prebuilt so
    the timed window measures ADMISSION, not data generation.  sha256 of
    a big tx releases the GIL, so the work the old global lock
    serialized is exactly the work the sharded path runs concurrently;
    the sizes skew big because on a small-core host the GIL-serialized
    per-insert bookkeeping would otherwise drown the lock-contention
    difference the A/B exists to measure."""
    sets = []
    for t in range(threads):
        if t == 0:
            size = 4 * 1024 * 1024  # the whale
        elif t == threads - 1 and threads > 2:
            size = 32 * 1024  # the spammer: many tiny txs
        else:
            size = 1024 * 1024  # small tenants
        ns = f"{t:02x}"
        sets.append((ns, [
            (f"{ns}:{i}:".encode() + b"x" * size) for i in range(per_thread)
        ]))
    return sets


def _mempool_inserts_per_sec(shards: int, tx_sets) -> tuple[float, float]:
    """(inserts/sec, MB/s admitted) for one leg: every thread inserts its
    tenant's txs into ONE pool, wall-clocked from a shared barrier."""
    import threading as _threading

    from celestia_app_tpu.mempool import PriorityMempool

    pool = PriorityMempool(
        max_tx_bytes=1 << 30, max_pool_bytes=1 << 62, shards=shards
    )
    threads = len(tx_sets)
    barrier = _threading.Barrier(threads + 1)

    def worker(ns, txs):
        barrier.wait()
        for i, tx in enumerate(txs):
            pool.insert(tx, priority=i, height=0, ns=ns)

    workers = [
        _threading.Thread(target=worker, args=s, daemon=True)
        for s in tx_sets
    ]
    for w in workers:
        w.start()
    barrier.wait()
    t0 = time.perf_counter()
    for w in workers:
        w.join()
    wall = time.perf_counter() - t0
    n = len(pool)
    total_mb = pool.size_bytes() / 1e6
    return (n / wall if wall else 0.0), (total_mb / wall if wall else 0.0)


def _mempool_ab_rows(la: float, platform: str) -> list[dict]:
    """The sharded-vs-global admission A/B rows: identical prebuilt tx
    sets, the frozen global-lock rung first, then the sharded pool; the
    global row carries the measured speedup (the repair_grouped
    pattern: the baseline exists to be compared against)."""
    import sys as _sys

    threads = max(2, int(os.environ.get("BENCH_THREADS", "8") or 8))
    per_thread = max(8, int(os.environ.get("BENCH_MEMPOOL_TXS", "32")
                            or 32))
    iters = max(1, int(os.environ.get("BENCH_MEMPOOL_ITERS", "3") or 3))
    from celestia_app_tpu.mempool import mempool_shards

    stripes = mempool_shards() or 8  # sharded leg ignores a global pin
    # The timed window measures the admission path, not the telemetry
    # plane: span/table writes are identical GIL-serialized work on both
    # rungs and would only dilute the lock-contention difference under
    # measurement.  The GIL switch interval is pinned low for BOTH legs:
    # a hash-released thread otherwise waits out the default 5 ms slice
    # to reacquire, which is handoff latency, not admission cost.
    saved_trace = os.environ.get("CELESTIA_TRACE")
    saved_si = _sys.getswitchinterval()
    os.environ["CELESTIA_TRACE"] = "off"
    _sys.setswitchinterval(0.0005)
    try:
        tx_sets = _mempool_tx_sets(threads, per_thread)
        # One warm leg (fresh small pool) pays the import + allocator
        # warmup + page-faulting the prebuilt tx bytes.
        _mempool_inserts_per_sec(0, _mempool_tx_sets(threads, 8))
        # Alternate the rungs so host-load drift hits both; each rung
        # records its best iteration (the same max-collapse bench_trend
        # applies to duplicate rows within a round).
        g_best = s_best = (0.0, 0.0)
        for _ in range(iters):
            g = _mempool_inserts_per_sec(0, tx_sets)
            s = _mempool_inserts_per_sec(stripes, tx_sets)
            g_best = max(g_best, g)
            s_best = max(s_best, s)
        g_rate, g_mb = g_best
        s_rate, s_mb = s_best
    finally:
        _sys.setswitchinterval(saved_si)
        if saved_trace is None:
            os.environ.pop("CELESTIA_TRACE", None)
        else:
            os.environ["CELESTIA_TRACE"] = saved_trace
    common = {"threads": threads, "txs_per_thread": per_thread,
              "loadavg": round(la, 2), "platform": platform}
    return [
        {"stage": f"mempool_sharded@{threads}", "mode": "mempool_sharded",
         "k": threads, "shards": stripes,
         "inserts_per_s": round(s_rate, 1), "mb_per_s": round(s_mb, 3),
         **common},
        {"stage": f"mempool_global@{threads}", "mode": "mempool_global",
         "k": threads, "shards": 0,
         "inserts_per_s": round(g_rate, 1), "mb_per_s": round(g_mb, 3),
         "speedup_sharded_vs_global": (
             round(s_rate / g_rate, 3) if g_rate else None
         ),
         **common},
    ]


# --------------------------------------------------------------------------
# child: run stages, append a JSON line per completed stage
# --------------------------------------------------------------------------


def _stage_plan() -> list[dict]:
    only_k = os.environ.get("BENCH_K")
    only_mode = os.environ.get("BENCH_MODE")
    if only_k or only_mode:
        # BENCH_K accepts a comma-separated list so one round can carry a
        # multi-k sweep (the compute_sharded 1-vs-N recipe runs k=256 and
        # k=512 in one record); a single value stays a single stage.
        ks = [int(tok) for tok in (only_k or "128").replace(",", " ").split()]
        mode = only_mode or "extend"
        plan = [{"mode": mode, "k": k} for k in ks]
        if mode == "mempool":
            # The admission A/B needs no device and no host baseline —
            # and one stage regardless of any BENCH_K sweep.
            return [{"mode": "mempool", "k": 0}]
        if mode != "host" and not os.environ.get("BENCH_BASELINE_S"):
            plan.append({"mode": "host", "k": min(min(ks), 128)})
        return plan
    # Device rows run FIRST and the CPU-heavy host baseline LAST: round 2's
    # driver bench showed device timings collapse ~25x under concurrent
    # host load, so nothing CPU-bound may precede them.  parts@512 leads:
    # it doubles as the autotuner, so every later row (incl. the headline
    # compute rows) runs on the fastest measured RS/SHA lowerings.
    # compute@512 runs twice (early and end of the device block) as a
    # stability check.
    plan = [
        {"mode": "parts", "k": 512},
        {"mode": "compute", "k": 512},
        {"mode": "compute", "k": 256},
        {"mode": "compute", "k": 128},
        {"mode": "extend", "k": 128},
        {"mode": "extend", "k": 256},
        {"mode": "extend", "k": 512},
        {"mode": "repair", "k": 128},
        {"mode": "repair", "k": 256},
        {"mode": "stream", "k": 128},
        {"mode": "compute", "k": 512, "rerun": True},
        {"mode": "host", "k": 128},
    ]
    if os.environ.get("BENCH_BASELINE_S"):
        plan = [s for s in plan if s["mode"] != "host"]
    return plan


def _run_child() -> None:
    results_path = os.environ["BENCH_RESULTS_FILE"]
    deadline = float(os.environ["BENCH_DEADLINE"])

    def emit(rec: dict) -> None:
        with open(results_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        # Same rows into the trace layer (a served node embedding the
        # bench exports them over GET /trace_tables; here they also feed
        # the parent's --metrics-out files).
        try:
            from celestia_app_tpu.trace import traced

            traced().write("bench_rows", **rec)
        except Exception:  # noqa: BLE001 — tracing never blocks a bench row
            pass

    import gc

    import jax

    platform = jax.devices()[0].platform
    emit({"stage": "probe", "platform": platform, "n_devices": len(jax.devices())})

    def loadavg() -> float:
        try:
            return os.getloadavg()[0]
        except OSError:
            return 0.0

    def wait_for_quiet(max_wait: float = 90.0, threshold: float = 2.0) -> float:
        """Device timings collapse under concurrent host load (round-2
        lesson); wait briefly for the 1-min loadavg to settle, then proceed
        regardless — the load value is recorded with the row."""
        t_end = time.monotonic() + max_wait
        la = loadavg()
        while la > threshold and time.monotonic() < t_end:
            time.sleep(5)
            la = loadavg()
        return la

    plan = _stage_plan()
    if platform == "cpu" and not (
        os.environ.get("BENCH_K") or os.environ.get("BENCH_MODE")
    ):
        # CPU fallback (wedged tunnel / no accelerator): k=512 device rows
        # take minutes per ITERATION on the 1-core host and would eat the
        # whole budget before the informative small-k rows run.  Scale the
        # default plan down; the emitted records carry platform="cpu" so
        # the run is never mistaken for a chip measurement.
        scaled = []
        for s in plan:
            t = dict(s, k=min(s["k"], 128))
            if t not in scaled:
                scaled.append(t)
        plan = scaled
        emit({"stage": "plan", "note": "cpu fallback: k capped at 128"})
    for stage in plan:
        mode, k = stage["mode"], stage["k"]
        name = f"{mode}@{k}" + ("#2" if stage.get("rerun") else "")
        remaining = deadline - time.monotonic()
        # Rough floor: big squares need compile + transfer headroom.
        need = 120 if (k >= 256 or mode == "host") else 60
        if remaining < need:
            emit({"stage": name, "skipped": "budget",
                  "remaining_s": round(remaining, 1)})
            continue
        if k > 512:
            default_iters = "2"  # giant k: minutes per iteration
        elif k >= 256 and mode != "compute":
            default_iters = "3"
        else:
            default_iters = "5"
        iters = int(os.environ.get("BENCH_ITERS", default_iters))
        la = wait_for_quiet() if mode != "host" else loadavg()
        t_start = time.monotonic()
        try:
            if mode == "mempool":
                for row in _mempool_ab_rows(la, platform):
                    emit({**row,
                          "wall_s": round(time.monotonic() - t_start, 1)})
                gc.collect()
                continue
            ods = _random_ods(k)
            ods_mb = ods.nbytes / 1e6
            if mode == "parts":
                parts = _parts_seconds(ods, max(iters, 3))
                tuned = parts.pop("tuned", None)
                # Candidate-level faults (a challenger that failed to
                # compile or run) ride out as <label>_error notes next to
                # the rows that DID measure.
                part_errors = {
                    p: parts.pop(p)
                    for p in [q for q in parts if q.endswith("_error")]
                }
                emit({
                    "stage": name, "mode": mode, "k": k,
                    "parts_seconds": {p: round(s, 4) for p, s in parts.items()},
                    **part_errors,
                    "tuned": tuned,
                    "mb": ods_mb,
                    "wall_s": round(time.monotonic() - t_start, 1),
                    "loadavg": round(la, 2), "platform": platform,
                })
                if tuned is not None:
                    # Autotune: every later stage (incl. the headline
                    # compute rows) rides the fastest measured lowerings
                    # and the winning fused-vs-staged pipeline.  Safe
                    # because nothing has built jit_pipeline yet — parts
                    # runs FIRST in the device block and uses fresh
                    # jax.jit wrappers, so the process-wide pipeline cache
                    # traces under this env.  An OPERATOR-set knob wins
                    # over the tuner: someone running the bench with
                    # CELESTIA_RS_FFT=on is measuring that path on
                    # purpose (parts saves/restores, so presence here
                    # means the operator set it).
                    target = _env_for_tuned(tuned)
                    for group in (
                        ("CELESTIA_RS_FFT", "CELESTIA_RS_FFT_MD",
                         "CELESTIA_RS_PALLAS", "CELESTIA_RS_XOR"),
                        ("CELESTIA_SHA_PALLAS", "CELESTIA_SHA_FUSED"),
                        ("CELESTIA_PIPE_FUSED",),
                    ):
                        if any(v in os.environ for v in group):
                            continue  # operator-set knob wins
                        _apply_env({v: target.get(v) for v in group})
                    # What later rows ACTUALLY run (operator knobs win
                    # over the tuner) — derived from the final env so the
                    # record can never contradict the headline rows.
                    emit({
                        "stage": "tuned-applied",
                        "applied": _applied_from_env(),
                    })
                gc.collect()
                continue
            if mode == "compute_sharded":
                # The multi-chip extend sweep: one row per ACTUAL shard
                # count over an identical plan (kernels/panel_sharded).
                # The panel seam must be on for the sharded rung to
                # engage; an operator-set height wins, otherwise the
                # recipe's 64-row default applies for the stage.
                saved_env = {
                    key: os.environ.get(key)
                    for key in ("CELESTIA_PIPE_PANEL",
                                "CELESTIA_EXTEND_SHARDS")
                }
                if not os.environ.get("CELESTIA_PIPE_PANEL"):
                    os.environ["CELESTIA_PIPE_PANEL"] = "64"
                measured: set[int] = set()
                try:
                    from celestia_app_tpu.kernels.panel_sharded import (
                        shards_for_k,
                    )

                    for want in _sharded_shard_counts():
                        # Dedupe on the POST-CLAMP actual count BEFORE
                        # burning the leg (the das-v2 sweep lesson): a
                        # clamped duplicate must cost a note, not a run.
                        os.environ["CELESTIA_EXTEND_SHARDS"] = (
                            str(want) if want > 1 else "0"
                        )
                        probe = shards_for_k(k) or 1
                        if probe in measured:
                            emit({"stage": f"compute_sharded{probe}@{k}",
                                  "skipped": "duplicate post-clamp shard "
                                             f"count (asked {want})"})
                            continue
                        t_leg = time.monotonic()
                        secs, actual = _compute_sharded_seconds(
                            ods, max(iters, 1), want
                        )
                        measured.add(actual)
                        emit({
                            "stage": f"compute_sharded{actual}@{k}",
                            "mode": f"compute_sharded{actual}", "k": k,
                            "shards": actual,
                            "seconds_per_block": secs, "mb": ods_mb,
                            "mb_per_s": round(ods_mb / secs, 3),
                            "wall_s": round(time.monotonic() - t_leg, 1),
                            "loadavg": round(la, 2),
                            "platform": platform,
                        })
                finally:
                    for key, val in saved_env.items():
                        if val is None:
                            os.environ.pop(key, None)
                        else:
                            os.environ[key] = val
                gc.collect()
                continue
            if mode == "host":
                secs = _host_seconds_per_block(ods)
                mb = ods_mb
            elif mode == "compute":
                # Giant squares take minutes per iteration on the CPU
                # fallback; 2 iterations still give a median while
                # letting BENCH_K=1024 finish inside a budget.  An
                # explicit BENCH_ITERS is the operator measuring
                # something ON PURPOSE (the README's one-iteration
                # peak-RSS recipe) and is never raised.
                floor = 1 if "BENCH_ITERS" in os.environ else (
                    5 if k <= 512 else 2)
                secs = _compute_seconds(ods, max(iters, floor))
                mb = ods_mb
            elif mode == "repair":
                secs = _repair_seconds(ods, iters)
                mb = 4 * ods_mb
            elif mode == "stream":
                secs = _stream_seconds(ods, iters)
                mb = ods_mb
            else:
                secs = _extend_seconds(ods, iters)
                mb = ods_mb
            emit({
                "stage": name, "mode": mode, "k": k,
                "seconds_per_block": secs, "mb": mb,
                "mb_per_s": round(mb / secs, 3),
                "wall_s": round(time.monotonic() - t_start, 1),
                "loadavg": round(la, 2),
                "platform": platform,
            })
            if (mode == "repair" and k == 128
                    and "CELESTIA_REPAIR_SWEEP" not in os.environ):
                # The batched-repair A/B (ISSUE 10 acceptance bar): the
                # headline repair row runs the default batched sweep; this
                # companion row re-measures the frozen per-pattern-group
                # baseline so the speedup is a recorded fact, not a claim.
                # Operator-set CELESTIA_REPAIR_SWEEP means they are
                # measuring one path on purpose — no A/B then.
                t_b = time.monotonic()
                os.environ["CELESTIA_REPAIR_SWEEP"] = "grouped"
                try:
                    gsecs = _repair_seconds(ods, max(1, min(iters, 2)))
                finally:
                    os.environ.pop("CELESTIA_REPAIR_SWEEP", None)
                emit({
                    "stage": f"repair_grouped@{k}",
                    "mode": "repair_grouped", "k": k,
                    "seconds_per_block": gsecs, "mb": mb,
                    "mb_per_s": round(mb / gsecs, 3),
                    "speedup_batched_vs_grouped": round(gsecs / secs, 3),
                    "wall_s": round(time.monotonic() - t_b, 1),
                    "loadavg": round(la, 2),
                    "platform": platform,
                })
            if mode == "stream":
                # The continuous-batching rows ride the stream stage:
                # blocks/sec at batch ∈ STREAM_BATCHES coalesced same-k
                # squares per dispatch.  One row per size (mode
                # stream_b<N>), rate-shaped like every other row so
                # bench_trend gates them as a series; batch-1 is the
                # unbatched control the coalesced sizes are judged
                # against (batch-B seconds/block < batch-1 means B
                # squares in one dispatch cost less than B dispatches).
                t_b = time.monotonic()
                for batch, bsecs in _stream_batched_seconds(ods, iters).items():
                    emit({
                        "stage": f"stream_b{batch}@{k}",
                        "mode": f"stream_b{batch}", "k": k, "batch": batch,
                        "seconds_per_block": bsecs, "mb": ods_mb,
                        "mb_per_s": round(ods_mb / bsecs, 3),
                        "blocks_per_s": round(1.0 / bsecs, 3),
                        "wall_s": round(time.monotonic() - t_b, 1),
                        "loadavg": round(la, 2),
                        "platform": platform,
                    })
        except Exception as e:  # noqa: BLE001 — record and move on
            emit({"stage": name, "error": f"{type(e).__name__}: {e}"[:500]})
        gc.collect()  # release the stage's device buffers before the next
    emit({"stage": "done"})


# --------------------------------------------------------------------------
# parent: probe, spawn child, assemble the single JSON line
# --------------------------------------------------------------------------


def _scrubbed_cpu_env(env: dict) -> dict:
    env = dict(env)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _terminate_gently(proc: subprocess.Popen, grace: float = 30.0) -> str:
    """SIGTERM + wait. Never SIGKILL: a killed TPU client can leak the
    accelerator relay's session grant and wedge every later client."""
    proc.terminate()
    try:
        proc.wait(timeout=grace)
        return "terminated"
    except subprocess.TimeoutExpired:
        print("bench: child ignored SIGTERM; abandoning it (no SIGKILL — "
              "see tpu relay grant-leak hazard)", file=sys.stderr)
        return "abandoned"


def _tunnel_listening() -> bool:
    """Fast pre-check of the loopback accelerator tunnel.

    The axon backend dials 127.0.0.1 relay ports; when the relay process is
    down, a JAX client retries the dead ports indefinitely (observed: the
    probe hangs until its timeout). A plain TCP connect distinguishes
    "relay down" (fail fast, no JAX client spawned at all) from "relay up
    but wedged" (probe with timeout as before).
    """
    if os.environ.get("AXON_LOOPBACK_RELAY") != "1":
        return True  # not tunnel-backed; nothing to pre-check
    ports_env = os.environ.get("BENCH_RELAY_PORTS", "8082,8083")
    try:
        ports = [int(p) for p in ports_env.split(",") if p.strip()]
    except ValueError:
        print(f"bench: ignoring malformed BENCH_RELAY_PORTS={ports_env!r}",
              file=sys.stderr)
        ports = [8082, 8083]
    for port in ports:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=2.0):
                return True
        except OSError:
            continue
    return False


def _probe_backend(timeout: float) -> str | None:
    """Return the default env's platform name, or None if unusable."""
    if not _tunnel_listening():
        print("bench: accelerator tunnel not listening; skipping backend "
              "probe (no JAX client spawned)", file=sys.stderr)
        return None
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print('PLATFORM=' + d[0].platform)"],
        cwd=_REPO_DIR,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _terminate_gently(proc, grace=15.0)
        print(f"bench: backend probe hung >{timeout:.0f}s (wedged tunnel?)",
              file=sys.stderr)
        return None
    if proc.returncode != 0:
        tail = (out or "").strip().splitlines()[-1:] or [""]
        print(f"bench: backend probe failed: {tail[0][:200]}", file=sys.stderr)
        return None
    for line in (out or "").splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def _run_measurement(env: dict, budget: float, results_path: str) -> None:
    env = dict(env)
    env["BENCH_RESULTS_FILE"] = results_path
    env["BENCH_DEADLINE"] = str(time.monotonic() + budget)
    env["_BENCH_CHILD"] = "1"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/celestia_jax_cache")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO_DIR, "bench.py")],
        cwd=_REPO_DIR, env=env,
        stdout=sys.stderr, stderr=sys.stderr,
    )
    try:
        proc.wait(timeout=budget + 120)
    except subprocess.TimeoutExpired:
        _terminate_gently(proc)


def _read_results(path: str) -> list[dict]:
    recs = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        recs.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    except FileNotFoundError:
        pass
    return recs


def _parse_metrics_out(argv: list[str]) -> str | None:
    """`--metrics-out <dir>` (or BENCH_METRICS_OUT): where the Prometheus
    textfile + JSONL tables land.  Hand-rolled so the no-flag invocation
    stays byte-compatible with every existing driver."""
    out = os.environ.get("BENCH_METRICS_OUT") or None
    args = list(argv)
    while "--metrics-out" in args:
        i = args.index("--metrics-out")
        if i + 1 >= len(args):
            print("bench: --metrics-out requires a directory", file=sys.stderr)
            break
        out = args[i + 1]
        del args[i : i + 2]
    return out


def _write_metrics_out(out_dir: str, recs: list[dict], summary: dict) -> None:
    """Write the bench's observability artifacts into `out_dir`:

      bench_metrics.prom  Prometheus textfile-collector exposition
                          (celestia_bench_* gauges/counters per row)
      bench_rows.jsonl    the tracer-table rows (one JSON object per
                          completed stage, the /trace_tables shape)

    Built from a PRIVATE registry/tracer: the files reflect this run only,
    never whatever else the process-wide registry accumulated.
    """
    from celestia_app_tpu.trace.metrics import Registry
    from celestia_app_tpu.trace.tracer import Tracer

    os.makedirs(out_dir, exist_ok=True)
    reg = Registry()
    # env_gated=False: these artifacts were explicitly requested; a
    # CELESTIA_TRACE=off perf run must not come back with empty files.
    tracer = Tracer(env_gated=False)
    rate = reg.gauge("celestia_bench_mb_per_s",
                     "per-stage ODS MB/s extended+DAH-hashed")
    secs = reg.gauge("celestia_bench_seconds_per_block",
                     "per-stage median seconds per block")
    errors = reg.counter("celestia_bench_errors_total",
                         "bench stages that raised")
    skipped = reg.counter("celestia_bench_stages_skipped_total",
                          "bench stages skipped (budget)")
    for rec in recs:
        if rec.get("stage") in ("probe", "plan", "done", "tuned-applied"):
            continue
        tracer.write("bench_rows", **rec)
        if "error" in rec:
            errors.inc(stage=str(rec.get("stage", "?")))
            continue
        if "skipped" in rec:
            skipped.inc(stage=str(rec.get("stage", "?")))
            continue
        # stage is part of the key: the compute@512 stability rerun ("#2")
        # shares {mode, k} with the primary and must not overwrite it.
        labels = {"mode": str(rec.get("mode", "?")), "k": str(rec.get("k", 0)),
                  "stage": str(rec.get("stage", "?"))}
        if "mb_per_s" in rec:
            rate.set(rec["mb_per_s"], **labels)
        if "seconds_per_block" in rec:
            secs.set(rec["seconds_per_block"], **labels)
    reg.gauge(
        "celestia_bench_headline_mb_per_s", "the summary line's headline rate"
    ).set(summary.get("value", 0))
    with open(os.path.join(out_dir, "bench_metrics.prom"), "w") as f:
        f.write(reg.render())
    with open(os.path.join(out_dir, "bench_rows.jsonl"), "w") as f:
        jsonl = tracer.export_jsonl("bench_rows")
        f.write(jsonl + "\n" if jsonl else "")


def main() -> None:
    if os.environ.get("_BENCH_CHILD") == "1":
        _run_child()
        return

    metrics_out = _parse_metrics_out(sys.argv[1:])

    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "1500"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
    t0 = time.monotonic()

    errors: list[str] = []
    platform = _probe_backend(probe_timeout)
    retry_ok = (os.environ.get("AXON_LOOPBACK_RELAY") == "1"
                and budget - (time.monotonic() - t0) > 60 + probe_timeout + 300)
    if platform is None and retry_ok and _tunnel_listening():
        # Bounded retry: the relay is up but the first probe failed — a
        # transient grant wedge sometimes clears after the stale client's
        # session lapses. One retry after a cool-down, then give up (the
        # relay is stdio-driven by the orchestrator; it cannot be reset
        # from inside this sandbox).
        errors.append("first backend probe failed with tunnel up; "
                      "retrying once after 60s cool-down")
        print("bench: tunnel up but probe failed; one retry in 60s",
              file=sys.stderr)
        time.sleep(60)
        platform = _probe_backend(probe_timeout)
    env = dict(os.environ)
    if platform is None:
        errors.append("default backend unusable; fell back to scrubbed CPU env")
        env = _scrubbed_cpu_env(env)
        platform = "cpu"

    fd, results_path = tempfile.mkstemp(prefix="bench_results_", suffix=".jsonl")
    os.close(fd)
    try:
        _run_measurement(env, budget - (time.monotonic() - t0), results_path)
        recs = _read_results(results_path)

        # The child's own backend init may still have failed — retry on CPU.
        # parts rows carry seconds (no mb_per_s) and count as success too.
        measured = [r for r in recs if "mb_per_s" in r or "parts_seconds" in r]
        if not measured and platform != "cpu":
            errors.append("measurement child produced no results on the "
                          "default backend; retrying on scrubbed CPU env")
            platform = "cpu"
            open(results_path, "w").close()  # drop the failed run's records
            _run_measurement(_scrubbed_cpu_env(env),
                             budget - (time.monotonic() - t0), results_path)
            recs = _read_results(results_path)
            measured = [r for r in recs if "mb_per_s" in r or "parts_seconds" in r]
    finally:
        try:
            os.unlink(results_path)
        except OSError:
            pass

    probe = next((r for r in recs if r.get("stage") == "probe"), None)
    if probe:
        platform = probe.get("platform", platform)
    errors.extend(r["error"] for r in recs if "error" in r)

    device = [r for r in measured if r["mode"] not in ("host", "parts")]
    host = next((r for r in measured if r["mode"] == "host"), None)
    parts_only = next((r for r in measured if "parts_seconds" in r), None)

    if not device and not host:
        out = {
            "metric": "ODS MB/s erasure-extended + DAH-hashed per chip",
            "value": 0, "unit": "MB/s", "vs_baseline": 0,
            "platform": platform,
        }
        if parts_only is not None:  # diagnostic BENCH_MODE=parts run
            out["parts"] = {
                "k": parts_only["k"], "seconds": parts_only["parts_seconds"],
                **({"tuned": parts_only["tuned"]} if parts_only.get("tuned") else {}),
            }
            if errors:  # rate stages may still have failed — say so
                out["errors"] = errors
        else:
            out["error"] = "; ".join(errors) or "no stage completed"
        if metrics_out:
            _write_metrics_out(metrics_out, recs, out)
        print(json.dumps(out))
        return

    # Headline: the largest compute row the plan actually ran (k=512, the
    # north-star size, unless the CPU fallback capped the plan).  Its two
    # runs bracket the device block; their spread is the stability figure
    # (VERDICT r2: an unstable headline is nearly as bad as none).
    comp = [r for r in device if r["mode"] == "compute"]
    if comp:
        k_head = max(r["k"] for r in comp)
        cpair = [r for r in comp if r["k"] == k_head]
        primary = min(cpair, key=lambda r: r["seconds_per_block"])
    else:
        cpair = []
        primary = device[0] if device else host
    stability_pct = None
    if len(cpair) >= 2:
        rates = sorted(r["mb_per_s"] for r in cpair)
        stability_pct = round(100 * (rates[-1] - rates[0]) / rates[0], 1)

    plan_capped = any(r.get("stage") == "plan" for r in recs)
    base_env = os.environ.get("BENCH_BASELINE_S")
    if base_env and plan_capped:
        # The operator's baseline was measured for the DEFAULT plan's
        # primary k; the CPU fallback rescaled the plan, so comparing
        # against it would be ~16x off.  Fall back to the host row.
        errors.append(
            "BENCH_BASELINE_S ignored: cpu fallback rescaled the plan, "
            "so the operator baseline's k no longer matches the primary"
        )
        base_env = None
    if base_env:
        # BENCH_BASELINE_S is seconds per block at the PRIMARY stage's k.
        from celestia_app_tpu.constants import SHARE_SIZE

        host_rate = primary["k"] ** 2 * SHARE_SIZE / 1e6 / float(base_env)
    elif host:
        host_rate = host["mb_per_s"]
    else:
        host_rate = None
    out = {
        "metric": (f"ODS MB/s erasure-extended + DAH-hashed per chip "
                   f"(k={primary['k']}, {primary['mode']}, {platform})"),
        "value": primary["mb_per_s"],
        "unit": "MB/s",
        "vs_baseline": (round(primary["mb_per_s"] / host_rate, 3)
                        if host_rate else 0),
        "platform": platform,
        "results": [
            {"mode": r["mode"], "k": r["k"], "mb_per_s": r["mb_per_s"],
             # The mempool A/B rows rate in inserts/sec + admitted MB/s
             # and have no per-block time; every device row keeps its
             # seconds_per_block.
             **({"seconds_per_block": round(r["seconds_per_block"], 4)}
                if "seconds_per_block" in r else {}),
             **({"inserts_per_s": r["inserts_per_s"]}
                if "inserts_per_s" in r else {}),
             **({"speedup_sharded_vs_global": r["speedup_sharded_vs_global"]}
                if "speedup_sharded_vs_global" in r else {}),
             **({"loadavg": r["loadavg"]} if "loadavg" in r else {}),
             **({"rerun": True} if r.get("stage", "").endswith("#2") else {})}
            for r in measured if "mb_per_s" in r  # parts rows lack rates
        ],
        "baseline_note": BASELINE_NOTE,
    }
    if parts_only is not None:
        applied = next(
            (r["applied"] for r in recs if r.get("stage") == "tuned-applied"),
            None,
        )
        out["parts"] = {
            "k": parts_only["k"], "seconds": parts_only["parts_seconds"],
            **({"tuned": parts_only["tuned"]} if parts_only.get("tuned") else {}),
            **({"applied": applied} if applied else {}),
        }
    if stability_pct is not None:
        out["stability_pct"] = stability_pct
    if errors:
        out["errors"] = errors
    if metrics_out:
        _write_metrics_out(metrics_out, recs, out)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Benchmark: MB/s erasure-extended + DAH-hashed per chip (BASELINE.json metric).

Measures the fused device pipeline (RS 2D extension + 4k NMT roots + DAH data
root; reference hot path app/prepare_proposal.go:61-71) end to end — host
ODS in, data root back on host — and compares against the straightforward
host-CPU path (numpy GF Reed-Solomon + hashlib SHA-256 NMTs), the in-image
proxy for the reference's Go leopard + crypto/sha256 implementation.

Prints ONE JSON line:
  {"metric": ..., "value": MB/s, "unit": "MB/s", "vs_baseline": x}

Env knobs:
  BENCH_K          square size (default 128)
  BENCH_ITERS      timed iterations (default 5)
  BENCH_BASELINE_S skip the CPU run, use the given seconds/block
  BENCH_MODE       extend (default) | repair (BASELINE config 4: quadrant
                   erasure decode) | stream (config 5: pipelined blocks,
                   dispatch overlapped with host work)
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _random_ods(k: int, seed: int = 3) -> np.ndarray:
    from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE

    rng = np.random.default_rng(seed)
    n = k * k
    ns = np.sort(rng.integers(0, 200, n).astype(np.uint8))
    ods = rng.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    return ods.reshape(k, k, SHARE_SIZE)


def _device_seconds_per_block(ods: np.ndarray, iters: int) -> float:
    """Full offload round trip: host ODS -> device pipeline -> host data root."""
    import jax

    from celestia_app_tpu.da.eds import ExtendedDataSquare

    ExtendedDataSquare.compute(ods).data_root()  # warmup / compile
    t0 = time.perf_counter()
    for _ in range(iters):
        eds = ExtendedDataSquare.compute(ods)
        eds.data_root()
    jax.effects_barrier()
    return (time.perf_counter() - t0) / iters


def _host_seconds_per_block(ods: np.ndarray) -> float:
    """CPU reference path: numpy GF RS extension + hashlib SHA-256 NMT trees."""
    from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
    from celestia_app_tpu.gf import codec_for_width
    from celestia_app_tpu.merkle import hash_from_byte_slices
    from celestia_app_tpu.nmt.hasher import NmtHasher

    k = ods.shape[0]
    codec = codec_for_width(k)
    t0 = time.perf_counter()
    row_parity = np.stack([codec.encode(ods[i]) for i in range(k)])
    top = np.concatenate([ods, row_parity], axis=1)  # (k, 2k, S)
    col_parity = np.stack([codec.encode(top[:, j]) for j in range(2 * k)], axis=1)
    eds = np.concatenate([top, col_parity], axis=0)  # (2k, 2k, S)

    parity = PARITY_NAMESPACE_BYTES

    def axis_roots(mat: np.ndarray) -> list[bytes]:
        roots = []
        for i in range(2 * k):
            digests = []
            for j in range(2 * k):
                share = mat[i, j].tobytes()
                in_q0 = i < k and j < k
                ns = share[:NAMESPACE_SIZE] if in_q0 else parity
                digests.append(NmtHasher.hash_leaf(ns + share))
            while len(digests) > 1:
                digests = [
                    NmtHasher.hash_node(digests[t], digests[t + 1])
                    for t in range(0, len(digests), 2)
                ]
            roots.append(digests[0])
        return roots

    row_roots = axis_roots(eds)
    col_roots = axis_roots(eds.transpose(1, 0, 2))
    hash_from_byte_slices(row_roots + col_roots)
    return time.perf_counter() - t0


def _repair_seconds(ods: np.ndarray, iters: int) -> float:
    """BASELINE config 4: quadrant erasure -> repair -> verified roots."""
    import jax

    from celestia_app_tpu.da import DataAvailabilityHeader, ExtendedDataSquare, repair

    k = ods.shape[0]
    eds = ExtendedDataSquare.compute(ods)
    dah = DataAvailabilityHeader.from_eds(eds)
    full = np.asarray(eds.squared())
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False  # 25% missing
    damaged = np.where(present[..., None], full, 0).astype(np.uint8)
    repair(damaged, present, dah)  # warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        repair(damaged, present, dah)
    jax.effects_barrier()
    return (time.perf_counter() - t0) / iters


def _stream_seconds(ods: np.ndarray, iters: int) -> float:
    """BASELINE config 5: pipelined block stream.

    Dispatch is async: block i+1's transfer+compute overlaps with
    retrieving block i's data root, the production overlap shape of the
    mainnet-replay config.
    """
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.da.eds import jit_pipeline

    k = ods.shape[0]
    pipe = jit_pipeline(k)
    blocks = [np.roll(ods, i, axis=0) for i in range(4)]
    jax.block_until_ready(pipe(jnp.asarray(blocks[0])))  # warmup
    t0 = time.perf_counter()
    pending = None
    n = 0
    for _ in range(iters):
        for b in blocks:
            out = pipe(jnp.asarray(b))
            if pending is not None:
                np.asarray(pending[3])  # fetch previous root (host sync)
            pending = out
            n += 1
    np.asarray(pending[3])
    return (time.perf_counter() - t0) / n


def main() -> None:
    k = int(os.environ.get("BENCH_K", "128"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))
    mode = os.environ.get("BENCH_MODE", "extend")
    ods = _random_ods(k)
    ods_mb = ods.nbytes / 1e6

    if mode == "repair":
        dev_s = _repair_seconds(ods, iters)
        metric = f"EDS MB/s quadrant-repaired + root-verified per chip (k={k})"
        mb = 4 * ods_mb
    elif mode == "stream":
        dev_s = _stream_seconds(ods, iters)
        metric = f"ODS MB/s pipelined extend+DAH per chip (k={k}, streamed)"
        mb = ods_mb
    else:
        dev_s = _device_seconds_per_block(ods, iters)
        metric = f"ODS MB/s erasure-extended + DAH-hashed per chip (k={k})"
        mb = ods_mb

    base_env = os.environ.get("BENCH_BASELINE_S")
    host_s = float(base_env) if base_env else _host_seconds_per_block(ods)

    value = mb / dev_s
    baseline = ods_mb / host_s
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 3),
                "unit": "MB/s",
                "vs_baseline": round(value / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
